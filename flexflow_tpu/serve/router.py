"""Multi-replica serving tier: prefix-affinity router + autoscaler.

One replica is done end-to-end (the sharded mixed program, the
disaggregated roles); "millions of users" is won or lost a layer
ABOVE it: which replica a request lands on decides whether its prompt
is a chain-hash prefix hit (near-zero prefill) or a cold re-prefill —
the dominant TTFT/goodput lever of the Gemma-on-TPU serving
comparison (PAPERS.md), and the serving-side analogue of the per-op
placement choices the SOAP search makes. This module is that tier
(docs/serving.md "Multi-replica routing"):

  * :class:`ReplicaPool` — N ``ServeEngine`` replicas over ONE model,
    each behind a long-lived :class:`~.engine.ServeSession` (the
    steppable engine hook), serving a TIMED traffic stream
    (serve/traffic.py) on a deterministic VIRTUAL clock: each
    replica's step advances its clock by the cost-model-priced step
    time (the same ``simulate_serve_step`` pricing the placement
    search and drift calibrator use), so TTFT/TPOT/goodput-under-SLO
    are reproducible numbers and autoscaler decisions replay exactly
    at one seed — while the TOKENS come from the real engines, so
    routed outputs stay token-identical to a single-replica engine.
  * prefix-affinity routing — route each request to the replica whose
    host-side chain-hash prefix registry holds the LONGEST matching
    prefix of its prompt (one dict probe per page-aligned block, plus
    the router's own pending-pin table so two same-tenant requests
    arriving back-to-back land together even before the first
    commits); tenant-sticky fallback hash when no replica matches;
    LOAD-AWARE SPILL — an affinity hit on a replica at degradation
    rung >= 3 (or past the occupancy ceiling) spills to the
    least-loaded replica rather than queueing behind a saturated
    pool.
  * :class:`Autoscaler` — a replica-count control loop whose
    decisions read ONLY exported :class:`MetricsRegistry` gauges (the
    pool publishes windowed TTFT/TPOT p99, per-replica occupancy,
    queue depth and demand each evaluation tick — no private engine
    state), with up/down hysteresis + cooldown so steady load never
    flaps, priced against the per-degree decode table
    ``search/serve_place.optimize_serve`` already returns (demand /
    priced per-replica capacity = the target count). Scale-ups
    reactivate a parked warm replica first — zero recompiles — and
    scale-downs drain before parking. Every decision lands as a
    telemetry span on the (serve, autoscaler) track.

Proved by ``tools/serve_bench.py --workload router`` (ci.sh step 1n):
affinity-routed vs round-robin on a multi-tenant prefix mix, gating
goodput-under-SLO >= 1.3x, token exactness vs a single replica for
every completed request, zero recompiles per replica after warmup,
and full page reclamation after drain.
"""

from __future__ import annotations

import dataclasses
import math
import os
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.telemetry import (MetricsRegistry, Telemetry, pct,
                               pow2_bucket, serve_metrics,
                               telemetry_for)
from .adapters import tenant_prefix_salt
from .engine import ServeEngine, ServeSession, StepEvents
from .host_tier import HostPageStore
from .kv_cache import prefix_page_keys
from .scheduler import Request, RequestOutcome
from .traffic import TrafficRequest

__all__ = ["Autoscaler", "Replica", "ReplicaPool"]

_ROUTER_TRACK = ("serve", "router")
_SCALER_TRACK = ("serve", "autoscaler")

# spin guard: consecutive planning-only (non-dispatched) steps one
# replica may return before the pool declares the scheduler wedged —
# the forced-progress rule makes real schedules converge in a couple
# of re-plans, so this only trips on a genuine bug
_MAX_PLAN_ONLY = 1000


def _tenant_hash(tenant: int) -> int:
    """Deterministic tenant-sticky hash (Knuth multiplicative — NOT
    Python's hash(), which is process-randomized for str and would
    unseed the router)."""
    return (int(tenant) * 2654435761) & 0xFFFFFFFF


class Replica:
    """One serving replica: an engine, its long-lived session, and the
    virtual clock the simulated cluster advances it on."""

    def __init__(self, idx: int, engine: ServeEngine):
        self.idx = idx
        self.engine = engine
        self.session: ServeSession = engine.start_session()
        self.clock_s = 0.0          # virtual time consumed
        self.busy_s = 0.0           # virtual seconds spent stepping
        self.steps = 0
        self.assigned = 0
        self.tokens = 0
        self.peak_occupancy = 0.0
        self.live = True            # parked (retired, warm) when False
        self.draining = False       # not routable; steps until empty
        self.inflight: set = set()  # stream ids tracked on this replica
        self._plan_only = 0
        # wall-clock mode: the step/submit mutual exclusion (the
        # worker thread holds it across session.step(), the router
        # thread across session.submit()) and the measured wall
        # seconds this replica's steps consumed
        self.lock = threading.Lock()
        self.busy_wall_s = 0.0
        # the zero-recompile baseline: compile counts right after
        # warmup — the router gate compares against THIS snapshot
        self.warm_counts = engine.compile_counts()

    # ---- backpressure signals (the spill + gauge inputs) -------------
    def occupancy(self) -> float:
        c = self.engine.cache_cfg
        return 1.0 - self.engine.cache.free_pages / c.usable_pages

    def rung(self) -> int:
        return int(self.session.sched.rung)

    def queue_depth(self) -> int:
        return len(self.session.sched.waiting)

    def routable(self) -> bool:
        return self.live and not self.draining

    def has_work(self) -> bool:
        return self.live and self.session.has_work()


class Autoscaler:
    """Telemetry-driven replica autoscaler.

    ``evaluate(t_now)`` reads ONLY gauges the pool exported into the
    shared :class:`MetricsRegistry` (serve_pool_ttft_p99_window_s,
    serve_pool_tpot_p99_window_s, serve_pool_occupancy_mean,
    serve_pool_queue_depth, serve_pool_decode_tokens_per_s_window,
    serve_pool_replicas_live, serve_pool_boot_cost_s) — never private
    engine state — so a
    decision is a pure function of (exported metrics, scaler state)
    and replays exactly at one seed. Hysteresis: scale up only after
    ``up_patience`` consecutive hot evaluations, down after
    ``down_patience`` cold ones, with a ``cooldown_s`` dead time
    after every action — a steady load settles, it never flaps.

    The per-degree decode table ``optimize_serve`` returns prices the
    decision: one replica sustains ``decode_lanes /
    decode_table[tp]`` tokens/sec, so the windowed demand divides
    into a TARGET replica count — demand above the live set's priced
    capacity is a scale-up signal even before the SLO breaks, and a
    scale-down is refused while the target says the remaining
    replicas could not carry the load."""

    def __init__(self, registry: MetricsRegistry, *,
                 slo_ttft_s: float = 0.0, slo_tpot_s: float = 0.0,
                 min_replicas: int = 1, max_replicas: int = 4,
                 interval_s: float = 1.0, occ_hi: float = 0.85,
                 occ_lo: float = 0.30, up_patience: int = 2,
                 down_patience: int = 4, cooldown_s: float = 0.0,
                 decode_table: Optional[Dict[int, float]] = None,
                 tensor_parallel: int = 1,
                 decode_lanes: Optional[int] = None,
                 mesh_table: Optional[Dict[Tuple[int, int],
                                           dict]] = None):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}")
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got "
                             f"{interval_s}")
        self.registry = registry
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_tpot_s = float(slo_tpot_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.occ_hi = float(occ_hi)
        self.occ_lo = float(occ_lo)
        self.up_patience = int(up_patience)
        self.down_patience = int(down_patience)
        self.cooldown_s = float(cooldown_s)
        # priced per-replica capacity from the search's decode table
        # (tokens/sec): lanes per decode step / simulated step seconds
        self.capacity_tps: Optional[float] = None
        if decode_table:
            step_s = decode_table.get(int(tensor_parallel)) \
                or min(decode_table.values())
            if step_s and decode_lanes:
                self.capacity_tps = float(decode_lanes) / float(step_s)
        # the 2-D mesh search's (t, r) price table
        # (ServeMeshPlacement.table): when present, target pricing
        # reads the searched pool-capacity column at THIS degree
        # instead of extrapolating the 1-D decode table — scale
        # decisions and placement agree on one price
        self.tensor_parallel = int(tensor_parallel)
        self.mesh_table = dict(mesh_table) if mesh_table else None
        self.events: List[dict] = []
        self._hot = 0
        self._cold = 0
        self._last_scale_t: Optional[float] = None

    @classmethod
    def from_config(cls, config, registry: MetricsRegistry,
                    **kw) -> "Autoscaler":
        """Build from FFConfig's --slo-ttft-ms/--slo-tpot-ms/
        --autoscale-max knobs (max 0 = 2x serve_replicas)."""
        sr = getattr(config, "serve_replicas", 1)
        n = 1 if isinstance(sr, str) else int(sr)   # "auto": the pool
        #   passes the searched count through max_replicas explicitly
        mx = int(getattr(config, "serve_autoscale_max", 0)) or 2 * n
        kw.setdefault("slo_ttft_s",
                      float(getattr(config, "slo_ttft_ms", 0.0)) / 1e3)
        kw.setdefault("slo_tpot_s",
                      float(getattr(config, "slo_tpot_ms", 0.0)) / 1e3)
        kw.setdefault("max_replicas", mx)
        return cls(registry, **kw)

    def target_replicas(self, demand_tps: float) -> Optional[int]:
        """Priced target count. With a 2-D mesh table: the smallest
        replica count whose searched (t, r) cell sustains the windowed
        token demand at this pool's tensor degree (extrapolated from
        the per-replica capacity past the priced grid). Otherwise the
        1-D path: windowed demand / decode-table capacity. None when
        no table was supplied."""
        if demand_tps <= 0:
            return None
        if self.mesh_table:
            rows = sorted(
                (int(r), cell) for (t, r), cell in
                self.mesh_table.items()
                if int(t) == self.tensor_parallel
                and float(cell.get("tokens_per_s", 0.0)) > 0)
            if rows:
                for r, cell in rows:
                    if float(cell["tokens_per_s"]) >= demand_tps:
                        return max(self.min_replicas, r)
                r1, c1 = rows[0]
                per = float(c1["tokens_per_s"]) / max(1, r1)
                return max(self.min_replicas,
                           math.ceil(demand_tps / per))
        if not self.capacity_tps:
            return None
        return max(self.min_replicas,
                   math.ceil(demand_tps / self.capacity_tps))

    def evaluate(self, t_now: float) -> Optional[dict]:
        """One control tick: returns a decision dict ({"direction":
        "up"|"down", "reason": ...}) or None. The pool applies it and
        emits the telemetry span."""
        m = self.registry
        live = int(m.gauge("serve_pool_replicas_live", 1.0))
        ttft99 = m.gauge("serve_pool_ttft_p99_window_s")
        tpot99 = m.gauge("serve_pool_tpot_p99_window_s")
        occ = m.gauge("serve_pool_occupancy_mean")
        queue = m.gauge("serve_pool_queue_depth")
        demand = m.gauge("serve_pool_decode_tokens_per_s_window")
        # what the NEXT scale-up costs (serve_pool_boot_cost_s,
        # ProgramRegistry-measured compile seconds): ~0 when a parked
        # replica or a --program-cache-dir snapshot makes the boot
        # warm, the measured compile storm when it would be cold —
        # attached to the decision so the cost is planning-visible
        # (it never gates the decision itself: an overloaded pool
        # must still scale, just with its eyes open)
        boot_s = m.gauge("serve_pool_boot_cost_s")
        target = self.target_replicas(demand)

        reasons = []
        if self.slo_ttft_s and ttft99 > self.slo_ttft_s:
            reasons.append(f"ttft_p99 {ttft99*1e3:.1f}ms > SLO")
        if self.slo_tpot_s and tpot99 > self.slo_tpot_s:
            reasons.append(f"tpot_p99 {tpot99*1e3:.1f}ms > SLO")
        if occ >= self.occ_hi:
            reasons.append(f"occupancy {occ:.0%} >= {self.occ_hi:.0%}")
        if target is not None and target > live:
            reasons.append(f"priced target {target} > {live} live")
        hot = bool(reasons)
        cold = (occ <= self.occ_lo and queue == 0
                and (not self.slo_ttft_s
                     or ttft99 <= 0.5 * self.slo_ttft_s)
                and (not self.slo_tpot_s
                     or tpot99 <= 0.75 * self.slo_tpot_s))
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0
        if self._last_scale_t is not None and \
                t_now - self._last_scale_t < self.cooldown_s:
            return None
        decision = None
        if self._hot >= self.up_patience and live < self.max_replicas:
            decision = {"direction": "up",
                        "reason": "; ".join(reasons)}
        elif self._cold >= self.down_patience \
                and live > self.min_replicas \
                and (target is None or target < live):
            decision = {"direction": "down",
                        "reason": f"occupancy {occ:.0%} <= "
                                  f"{self.occ_lo:.0%}, queue empty, "
                                  f"latency well under SLO"}
        if decision is not None:
            decision.update(
                t=t_now, live=live, ttft_p99_s=ttft99,
                tpot_p99_s=tpot99, occupancy=occ, queue_depth=queue,
                demand_tokens_per_s=demand, priced_target=target,
                boot_s=boot_s)
            self.events.append(decision)
            self._hot = self._cold = 0
            self._last_scale_t = t_now
        return decision


class ReplicaPool:
    """N serving replicas over one model, behind the prefix-affinity
    router, driven on a deterministic virtual clock (module
    docstring). ``run(traffic, ...)`` serves a seeded
    :mod:`~.traffic` stream and returns (and stashes on
    ``last_stats``) the per-request records + goodput-under-SLO the
    bench A/Bs; :meth:`route`/:meth:`submit`/:meth:`step_next` are
    the underlying pieces the tests drive directly."""

    def __init__(self, model, num_replicas: Optional[int] = None, *,
                 policy: Optional[str] = None, config=None,
                 telemetry: Optional[Telemetry] = None,
                 spill_rung: int = 3, spill_occupancy: float = 0.90,
                 window_s: float = 2.0, engine_kwargs=None):
        if model.state is None:
            from ..config import CompMode
            model.compile(comp_mode=CompMode.INFERENCE)
        self.model = model
        cfg = config if config is not None else model.config
        self.config = cfg
        engine_kwargs = dict(engine_kwargs or {})
        # 2-D auto-placement (--serve-replicas auto, docs/search.md
        # "2-D serve mesh"): ONE search prices tensor degree x replica
        # count x torus-axis assignment over the device budget and the
        # pool boots the searched (t, r) shape — an explicit
        # --serve-mesh N pins the degree and only the count is
        # searched; --serve-mesh auto lets the walk price both. The
        # placement is stashed on self.mesh_placement (the autoscaler's
        # target pricing and router_report read it).
        self.mesh_placement = None
        sr = getattr(cfg, "serve_replicas", 1)
        if num_replicas is None and isinstance(sr, str) \
                and sr.strip() == "auto":
            import jax
            from ..search.serve_place import optimize_serve_mesh
            from .engine import probe_serve_arch
            sm = str(getattr(cfg, "serve_mesh", "") or "").strip()
            fixed_t = int(sm) if sm and sm != "auto" else None
            if "tensor_parallel" in engine_kwargs:
                fixed_t = int(engine_kwargs["tensor_parallel"])
            place = optimize_serve_mesh(
                probe_serve_arch(model, cfg), len(jax.devices()),
                config=cfg, fixed_tensor=fixed_t)
            self.mesh_placement = place
            num_replicas = place.replicas
            engine_kwargs.setdefault("tensor_parallel",
                                     place.tensor_parallel)
        if num_replicas is None:
            num_replicas = int(getattr(cfg, "serve_replicas", 1))
        if num_replicas < 1:
            raise ValueError(
                f"need >= 1 replica, got {num_replicas}")
        self.policy = policy if policy is not None \
            else str(getattr(cfg, "router_policy", "affinity"))
        if self.policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"router policy must be 'affinity' or 'round_robin', "
                f"got {self.policy!r}")
        self.telemetry = telemetry if telemetry is not None \
            else telemetry_for(cfg)
        # the pool-lifetime registry: replica-labeled latency folds,
        # router/autoscaler counters, and the gauges the autoscaler
        # reads. The bus's registry when telemetry is on (one scrape
        # surface), else the pool's own — never the shared disabled
        # singleton's (the DisaggCluster idiom).
        self.metrics = self.telemetry.metrics if self.telemetry.enabled \
            else MetricsRegistry()
        self.spill_rung = int(spill_rung)
        self.spill_occupancy = float(spill_occupancy)
        self.window_s = float(window_s)
        self._engine_kwargs = dict(engine_kwargs or {})
        # ONE shared host tier for the whole pool (hierarchical
        # prefix cache, serve/host_tier.py): every replica spills
        # into and reloads from the same store, so a tenant's
        # preamble crosses HBM once per replica instead of once per
        # request. An explicit engine_kwargs["host_tier"] wins (tests
        # inject a store); otherwise --host-tier-mb arms it.
        ht = self._engine_kwargs.get("host_tier")
        if ht is None \
                and bool(getattr(cfg, "serve_host_tier", True)) \
                and float(getattr(cfg, "host_tier_mb", 0.0)
                          or 0.0) > 0:
            ht = HostPageStore(float(cfg.host_tier_mb))
        self.host_tier: Optional[HostPageStore] = ht
        if ht is not None:
            self._engine_kwargs["host_tier"] = ht
        # pool-wide adapter registry (tenant -> (weights, scale)):
        # replayed onto every replica — including engines the
        # autoscaler builds later — so any replica can serve any
        # registered tenant (serve/adapters.py)
        self._adapter_registry: Dict[int, tuple] = {}
        self.replicas: List[Replica] = []
        self._pins: List[Dict[bytes, int]] = []
        self._rr_next = 0
        self._sample_seed = 0
        self._inflight: Dict[int, dict] = {}    # stream id -> tracked
        self._records: Dict[int, dict] = {}
        self._req_refs: Dict[int, Request] = {}  # stream id -> Request
        self._w_first: deque = deque()   # (t_first, ttft)
        self._w_done: deque = deque()    # (t_finish, tpot, tokens)
        # which clock the CURRENT run's latency stamps are on
        # ("virtual" | "wall") — _finalize labels its exported
        # histograms with it, so wall-mode samples can never pollute
        # the serve_router_*_virtual_seconds series (and vice versa)
        self._clock = "virtual"
        self._next_eval = 0.0
        self.scale_events: List[dict] = []
        self.stats = {"routed": 0, "affinity_hits": 0,
                      "host_hits": 0,
                      "adapter_affinity_hits": 0, "spills": 0,
                      "fallbacks": 0, "cancels_sent": 0,
                      "scale_ups": 0, "scale_downs": 0}
        self.last_stats: Optional[dict] = None
        # most recent replica-boot record (_activate_replica): warm vs
        # cold, wall seconds, and the registry's measured compile
        # seconds — exported as serve_pool_boot_cost_s so the
        # autoscaler's scale-up decision prices the boot it is about
        # to pay
        self._last_boot: Optional[dict] = None
        for _ in range(int(num_replicas)):
            self._activate_replica(0.0)
        # the pool owns the scrape endpoint (replica engines are built
        # with metrics_port=None): one /metrics page serves the whole
        # tier — labeled latency series, router counters, autoscaler
        # gauges — exactly what an external autoscaler would poll
        self.metrics_server = None
        mport = getattr(cfg, "metrics_port", None)
        if mport is not None:
            from ..utils.telemetry import MetricsServer
            self.metrics_server = MetricsServer(
                self.metrics.to_prometheus, port=int(mport),
                host=str(getattr(cfg, "metrics_host", "127.0.0.1")))

    @classmethod
    def from_config(cls, model, **kw) -> "ReplicaPool":
        """--serve-replicas/--router-policy construction."""
        return cls(model, **kw)

    # ---------------- replica lifecycle --------------------------------
    def _new_engine(self) -> ServeEngine:
        role_cfg = dataclasses.replace(self.config, metrics_port=None)
        return ServeEngine(self.model, chunked_prefill=True,
                           telemetry=self.telemetry, config=role_cfg,
                           **self._engine_kwargs)

    def _activate_replica(self, t_now: float) -> Replica:
        """Scale-up primitive, cheapest boot first: reactivate a
        PARKED warm replica (compiled programs intact — zero
        recompiles); else build a fresh engine, which boots WARM from
        --program-cache-dir when the ProgramRegistry snapshot covers
        this config (executables deserialize instead of compiling) and
        cold otherwise. Every non-parked boot emits a `replica_boot`
        span labeled warm/cold with the registry's measured compile
        seconds, and the latest boot cost feeds the
        serve_pool_boot_cost_s gauge the autoscaler prices scale-ups
        with. The new replica's clock fast-forwards to now (a replica
        cannot serve the past)."""
        for r in self.replicas:
            if not r.live:
                r.live = True
                r.draining = False
                r.clock_s = max(r.clock_s, t_now)
                self._last_boot = {"warm": True, "parked": True,
                                   "boot_s": 0.0, "compile_s": 0.0,
                                   "restored": 0, "compiles": 0}
                return r
        w0 = time.perf_counter()
        eng = self._new_engine()
        for t, (w, sc) in sorted(self._adapter_registry.items()):
            eng.register_adapter(t, w, scale=sc)
        eng.set_track_process(f"replica{len(self.replicas)}")
        eng.warmup()
        w1 = time.perf_counter()
        bs = eng.boot_stats or {}
        self._last_boot = {
            "warm": bool(bs.get("warm")), "parked": False,
            "boot_s": w1 - w0,
            "compile_s": float(bs.get("compile_s", 0.0)),
            "restored": int(bs.get("restored", 0)),
            "compiles": int(bs.get("compiles", 0))}
        if self.telemetry.enabled:
            self.telemetry.span(
                _SCALER_TRACK,
                f"replica_boot_"
                f"{'warm' if self._last_boot['warm'] else 'cold'}",
                w0, w1,
                args={"replica": len(self.replicas),
                      "t_virtual": t_now, **self._last_boot})
        r = Replica(len(self.replicas), eng)
        r.clock_s = t_now
        self.replicas.append(r)
        self._pins.append({})
        return r

    def register_adapter(self, tenant_id: int, weights, *,
                         scale: float = 1.0) -> None:
        """Register a tenant's LoRA adapter on EVERY replica (and on
        replicas the autoscaler activates later): the router may land
        the tenant anywhere, so the registry must be pool-uniform —
        residency (which replica holds the tenant's slab SLOT) is what
        adapter-affinity routing differentiates, not registration."""
        self._adapter_registry[int(tenant_id)] = (weights, float(scale))
        for r in self.replicas:
            r.engine.register_adapter(tenant_id, weights, scale=scale)

    def routable(self) -> List[Replica]:
        return [r for r in self.replicas if r.routable()]

    def compile_counts(self) -> Dict[str, Dict[str, int]]:
        return {f"replica{r.idx}": r.engine.compile_counts()
                for r in self.replicas}

    def assert_zero_recompiles(self) -> None:
        """The router gate: no replica compiled anything after ITS
        warmup (replicas added by the autoscaler snapshot at their own
        activation)."""
        for r in self.replicas:
            now = r.engine.compile_counts()
            assert now == r.warm_counts, (
                f"replica{r.idx} recompiled: {r.warm_counts} -> {now}")

    def check_drained(self) -> None:
        """Post-drain invariants: every pool clean, every page
        reclaimed (prefix-parked pages are refcount-0 reclaimable and
        count as free)."""
        for r in self.replicas:
            r.engine.cache.check_invariants()
            c = r.engine.cache_cfg
            free = r.engine.cache.free_pages
            assert free == c.usable_pages, (
                f"replica{r.idx} leaked pages: {free} free of "
                f"{c.usable_pages}")

    def close(self) -> None:
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()
        for r in self.replicas:
            r.session.close()
            r.engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------- routing ------------------------------------------
    def route(self, prompt: Sequence[int], tenant: int = 0
              ) -> Tuple[Replica, dict]:
        """Pick the replica for one prompt. Affinity: longest
        chain-hash prefix match over every routable replica's page
        registry (extended through the router's pending pins), ties to
        the lowest replica id; tenant-sticky hash fallback on a total
        miss; load-aware spill off rung/occupancy pressure. Pure
        observation — the caller submits (and pins) via submit()."""
        live = self.routable()
        if not live:
            raise RuntimeError("no routable replicas")
        ps = live[0].engine.cache_cfg.page_size
        npages = max(0, (len(prompt) - 1) // ps)
        # a tenant is an ADAPTER tenant only if the pool registered
        # one; otherwise the id is a pure routing-affinity key and the
        # lane serves the base model (PR 14 semantics, tenant_id=0)
        adapted = int(tenant) != 0 and int(tenant) in \
            self._adapter_registry
        # the probe keys carry the tenant's prefix salt — an adapted
        # tenant's pages hash on a disjoint chain (adapters.
        # tenant_prefix_salt), so the router's registry probe matches
        # exactly the pages admission would attach
        keys = prefix_page_keys(
            prompt, ps, npages,
            prev=tenant_prefix_salt(tenant) if adapted else b"") \
            if npages else []
        info = {"tenant": int(tenant), "adapted": adapted,
                "matched_tokens": 0,
                "affinity_hit": False, "host_hit": False,
                "adapter_affinity": False,
                "fallback": False, "spilled": False, "keys": keys}
        if self.policy == "round_robin":
            target = live[self._rr_next % len(live)]
            self._rr_next += 1
            return target, info
        best = None
        best_pages = 0
        for r in live:
            # the registry probe: one dict hit per page-aligned block
            k = len(r.engine.cache.match_prefix(keys))
            pins = self._pins[r.idx]
            while k < len(keys) and keys[k] in pins:
                k += 1
            if k > best_pages:
                best, best_pages = r, k
        if best is not None:
            target = best
            info["affinity_hit"] = True
            info["matched_tokens"] = best_pages * ps
        else:
            # adapter affinity, the tier between prefix affinity and
            # the blind hash: with no page match, a replica where the
            # tenant's adapter is already RESIDENT (slab loaded —
            # mapped or LRU-parked) skips the admission load stall.
            # Ties to the least-loaded such replica; the plain
            # tenant-sticky hash only when no replica holds it.
            resident = [r for r in live
                        if adapted
                        and r.engine.adapter_resident(tenant)]
            # host-tier affinity, the second tier below an HBM hit:
            # the SHARED store can reload the prefix into ANY
            # replica (priced DMA vs recompute at admission), so
            # land on the least-loaded one — preferring a replica
            # where the tenant's adapter is already resident
            host_pages = (self.host_tier.probe_chain(keys)
                          if self.host_tier is not None and keys
                          else 0)
            if host_pages > 0:
                pool = resident if resident else live
                target = min(pool, key=lambda x: (x.occupancy(),
                                                  x.queue_depth(),
                                                  x.idx))
                info["host_hit"] = True
                info["adapter_affinity"] = bool(resident)
                info["matched_tokens"] = host_pages * ps
            elif resident:
                target = min(resident, key=lambda x: (x.occupancy(),
                                                      x.queue_depth(),
                                                      x.idx))
                info["adapter_affinity"] = True
            else:
                target = live[_tenant_hash(tenant) % len(live)]
                info["fallback"] = True
        if len(live) > 1 and (target.rung() >= self.spill_rung
                              or target.occupancy()
                              >= self.spill_occupancy):
            # backpressure spill: queueing an affinity hit behind a
            # saturated pool costs more than a cold prefill elsewhere
            alt = min(live, key=lambda x: (x.occupancy(),
                                           x.queue_depth(), x.idx))
            if alt is not target \
                    and alt.occupancy() < target.occupancy():
                target = alt
                info["spilled"] = True
        return target, info

    def _pin(self, replica: Replica, keys: List[bytes]) -> None:
        pins = self._pins[replica.idx]
        for k in keys:
            pins[k] = pins.get(k, 0) + 1

    def _release_pins(self, tracked: dict) -> None:
        """Drop a request's affinity pins (terminal outcome or
        cancel): a pin held past its request would keep steering
        tenants at a replica that may never commit those pages."""
        if tracked.get("pins_released"):
            return
        tracked["pins_released"] = True
        pins = self._pins[tracked["replica"]]
        for k in tracked["keys"]:
            n = pins.get(k, 0) - 1
            if n <= 0:
                pins.pop(k, None)
            else:
                pins[k] = n

    def submit(self, tr: TrafficRequest, *,
               eos_token: Optional[int] = None) -> dict:
        """Route + submit one traffic request, returning its tracking
        record. The sampling stream keys to ``tr.stream_id``, so the
        emitted tokens are identical on ANY replica (and to a single
        engine serving the same stream ids)."""
        if tr.stream_id in self._inflight \
                or tr.stream_id in self._records:
            raise ValueError(
                f"stream id {tr.stream_id} already submitted")
        # trace context is minted HERE — the first tier that sees the
        # request — and rides the Request into whichever replica wins,
        # so the routing decision and every downstream engine span
        # share one causally-linked timeline (docs/observability.md)
        from ..utils.telemetry import next_trace_id
        trace_id = next_trace_id()
        t_route0 = time.perf_counter()
        replica, info = self.route(tr.prompt, tenant=tr.tenant)
        eng = replica.engine
        sample = None
        if tr.temperature and float(tr.temperature) > 0.0:
            sample = eng._sample_params(
                tr.temperature, tr.top_k, self._sample_seed, 1,
                eng.topk_cap)[0]
        # an idle replica starts serving at the arrival instant, not
        # at whatever its clock last drained to (virtual mode only —
        # wall mode never reads clock_s, and stamping traffic-plan
        # times into it would corrupt a later virtual run's clocks)
        if self._clock == "virtual" and not replica.session.has_work():
            replica.clock_s = max(replica.clock_s, tr.t_arrival)
        req = replica.session.submit(
            tr.prompt, tr.max_new, eos_token=eos_token, sample=sample,
            stream_id=tr.stream_id, trace_id=trace_id,
            tenant_id=tr.tenant if info["adapted"] else 0)
        tracked = {
            "stream_id": tr.stream_id, "tenant": tr.tenant,
            "replica": replica.idx, "req": req,
            "trace_id": trace_id,
            "t_arrival": tr.t_arrival, "t_first": None,
            "t_finish": None, "tokens_emitted": 0,
            "cancel_after": tr.cancel_after_tokens,
            "cancel_sent": False, "sampled": tr.sampled,
            "affinity_hit": info["affinity_hit"],
            "host_hit": info["host_hit"],
            "adapter_affinity": info["adapter_affinity"],
            "spilled": info["spilled"], "fallback": info["fallback"],
            "matched_tokens": info["matched_tokens"],
            "keys": info["keys"], "pins_released": False,
        }
        self._pin(replica, info["keys"])
        self._inflight[tr.stream_id] = tracked
        replica.inflight.add(tr.stream_id)
        replica.assigned += 1
        self.stats["routed"] += 1
        m = self.metrics
        m.inc("router_requests_total", replica=str(replica.idx))
        if info["affinity_hit"]:
            self.stats["affinity_hits"] += 1
            m.inc("router_affinity_hits_total")
        if info["host_hit"]:
            self.stats["host_hits"] += 1
            m.inc("router_host_hits_total")
        if info["adapter_affinity"]:
            self.stats["adapter_affinity_hits"] += 1
            m.inc("router_adapter_affinity_hits_total")
        if info["fallback"]:
            self.stats["fallbacks"] += 1
            m.inc("router_fallback_total")
        if info["spilled"]:
            self.stats["spills"] += 1
            m.inc("router_spills_total")
        if self.telemetry.enabled:
            # the routing decision is a SPAN (wall time the router
            # spent matching/spilling, the "routing" component of
            # explain_request) with the trace id every downstream
            # engine span shares; the legacy "route" instant keeps its
            # one-line decision record
            self.telemetry.span(
                _ROUTER_TRACK, "routing", t_route0,
                time.perf_counter(),
                args={"trace": trace_id, "stream": tr.stream_id,
                      "replica": replica.idx})
            self.telemetry.instant(
                _ROUTER_TRACK, "route",
                args={"stream": tr.stream_id, "tenant": tr.tenant,
                      "trace": trace_id,
                      "replica": replica.idx,
                      "matched_tokens": info["matched_tokens"],
                      "affinity": info["affinity_hit"],
                      "spilled": info["spilled"],
                      "t_virtual": tr.t_arrival})
        return tracked

    def cancel(self, stream_id: int) -> bool:
        """Host-side cancel by stream id (a user abandoning
        mid-generation — or mid-QUEUE: a waiting request aborts at its
        replica's next chunk boundary). The affinity pin reclaims
        immediately — routing must stop steering the tenant at a
        replica that will never commit those pages."""
        tracked = self._inflight.get(stream_id)
        if tracked is None:
            return False
        replica = self.replicas[tracked["replica"]]
        ok = replica.engine.cancel(tracked["req"].rid)
        if ok:
            tracked["cancel_sent"] = True
            self.stats["cancels_sent"] += 1
            self.metrics.inc("router_cancels_total")
        self._release_pins(tracked)
        return ok

    # ---------------- virtual-clock pricing ----------------------------
    def _price(self, replica: Replica, ev: StepEvents) -> float:
        """Virtual seconds of one mixed step: the SAME cost-stack
        pricing the placement search and the drift calibrator use
        (engine._drift_predicted -> simulate_serve_step at the
        engine's fixed lane width, cached per context bucket), with a
        deterministic analytic fallback when the cost stack cannot
        price the arch. Deterministic by construction — the whole
        virtual cluster replays at one seed."""
        eng = replica.engine
        ctx_b = pow2_bucket(max(1, ev.ctx_mean))
        pred = eng._drift_predicted(ctx_b)
        if pred is not None:
            return float(pred[0])
        return 1e-4 * (1.0 + eng.mixed_width / 512.0) \
            * (1.0 + ctx_b / 2048.0)

    def price_probe(self, ctx: int = 64) -> float:
        """The virtual step price at a typical context — what the
        bench derives SLO targets and arrival rates from, so the
        workload scales with the priced engine instead of hardcoding
        wall seconds."""
        ev = StepEvents()
        ev.ctx_mean = int(ctx)
        return self._price(self.replicas[0], ev)

    def _host_tier_block(self) -> Optional[dict]:
        """The pool-level host-tier block of last_stats: the SHARED
        store's lifetime report merged with the per-engine reload
        decision counters summed across replicas (each engine prices
        its own reloads; the store is one). Also corrects the
        registry: the per-replica serve_metrics folds counter_set the
        per-engine reload counters, so the last replica's value would
        otherwise shadow the rest — re-set the pool-wide sums."""
        if self.host_tier is None:
            return None
        host = dict(self.host_tier.report())
        for k in ("reload_events", "reload_pages", "spilled_pages",
                  "recompute_chosen"):
            host[k] = sum(
                int(r.engine._host_reload_stats.get(k, 0))
                for r in self.replicas)
        host["reload_priced_s"] = sum(
            float(r.engine._host_reload_stats.get(
                "reload_priced_s", 0.0))
            for r in self.replicas)
        m = self.metrics
        m.counter_set("serve_host_tier_reload_pages_total",
                      host["reload_pages"])
        m.counter_set("serve_host_tier_recompute_chosen_total",
                      host["recompute_chosen"])
        return host

    def _mesh_block(self) -> Optional[dict]:
        """The 2-D placement block of last_stats (--serve-replicas
        auto): the chosen (t, r) cell with its priced goodput, every
        rejected neighbor cell with ITS price, and the HBM-infeasible
        degrees — the chosen-vs-rejected discipline router_report and
        tools/explain.py render from. None on explicitly-sized
        pools."""
        p = self.mesh_placement
        if p is None:
            return None
        cells = {}
        for (t, r), cell in p.table.items():
            cells[f"{t}x{r}"] = {
                k: cell[k] for k in ("goodput_per_s", "tokens_per_s",
                                     "tpot_s", "ttft_s")}
        return {
            "tensor_parallel": p.tensor_parallel,
            "replicas": p.replicas,
            "tensor_axis_dims": list(p.tensor_axis_dims),
            "data_axis_dims": list(p.data_axis_dims),
            "goodput_per_s": p.goodput_per_s,
            "num_devices": p.num_devices,
            "table": cells,
            "infeasible": [dict(d) for d in p.infeasible],
        }

    # ---------------- the serving loop ---------------------------------
    def _finalize(self, tracked: dict, t_end: float,
                  slo_ttft_s: Optional[float],
                  slo_tpot_s: Optional[float]) -> None:
        req: Request = tracked["req"]
        sid = tracked["stream_id"]
        self._inflight.pop(sid, None)
        self.replicas[tracked["replica"]].inflight.discard(sid)
        self._release_pins(tracked)
        tokens = list(req.out_tokens)
        ttft = (tracked["t_first"] - tracked["t_arrival"]
                if tracked["t_first"] is not None else None)
        tpot = 0.0
        if tracked["t_first"] is not None and len(tokens) > 1:
            tpot = (t_end - tracked["t_first"]) / (len(tokens) - 1)
        completed = req.outcome == RequestOutcome.COMPLETED
        slo_ok = completed and ttft is not None \
            and (not slo_ttft_s or ttft <= slo_ttft_s) \
            and (not slo_tpot_s or tpot <= slo_tpot_s)
        self._records[sid] = {
            "stream_id": sid, "tenant": tracked["tenant"],
            "replica": tracked["replica"],
            "trace_id": tracked["trace_id"],
            "outcome": req.outcome, "tokens": tokens,
            "t_arrival": tracked["t_arrival"],
            "ttft_s": ttft, "tpot_s": tpot, "t_finish": t_end,
            "slo_ok": slo_ok, "sampled": tracked["sampled"],
            "affinity_hit": tracked["affinity_hit"],
            "host_hit": tracked["host_hit"],
            "adapter_affinity": tracked["adapter_affinity"],
            "spilled": tracked["spilled"],
            "fallback": tracked["fallback"],
            "matched_tokens": tracked["matched_tokens"],
            "cancelled_by_router": tracked["cancel_sent"],
        }
        self._req_refs[sid] = req   # explain_request / attribution
        self._w_done.append((t_end, tpot, len(tokens)))
        m = self.metrics
        # SLO error-budget accounting (utils/slo.py reads ONLY these
        # exported counters): every finalized request except a
        # router-sent cancel (a user abandon is not the tier's error)
        # enters the denominator; a violation is any counted request
        # that missed — a completed one past target, or one the tier
        # failed outright (rejected / deadline / failed), labeled by
        # which bound (or outcome) it burned
        if (slo_ttft_s or slo_tpot_s) \
                and not tracked["cancel_sent"] \
                and req.outcome != RequestOutcome.CANCELLED:
            m.inc("serve_slo_requests_total")
            if not slo_ok:
                m.inc("serve_slo_violations_total")
                if not completed:
                    m.inc("serve_slo_violations_total", slo="outcome")
                else:
                    if slo_ttft_s and (ttft is None
                                       or ttft > slo_ttft_s):
                        m.inc("serve_slo_violations_total", slo="ttft")
                    if slo_tpot_s and tpot > slo_tpot_s:
                        m.inc("serve_slo_violations_total", slo="tpot")
        if ttft is not None:
            m.observe(f"serve_router_ttft_{self._clock}_seconds",
                      ttft)
            self._w_first.append((tracked["t_first"], ttft))
        if tpot:
            m.observe(f"serve_router_tpot_{self._clock}_seconds",
                      tpot)
        m.inc("router_requests_finished_total", outcome=req.outcome)

    def _sweep_terminal(self, replica: Replica, t_end: float,
                        slo_ttft_s, slo_tpot_s) -> None:
        done = [sid for sid in replica.inflight
                if self._inflight[sid]["req"].outcome
                != RequestOutcome.PENDING]
        for sid in done:
            self._finalize(self._inflight[sid], t_end, slo_ttft_s,
                           slo_tpot_s)

    def _export_gauges(self, t_now: float) -> None:
        """Publish the autoscaler's decision inputs into the shared
        registry — per-replica occupancy/rung, pool occupancy mean,
        queue depth, and the windowed virtual TTFT/TPOT p99 + token
        demand. The autoscaler reads ONLY these."""
        m = self.metrics
        routable = self.routable()
        m.set("serve_pool_replicas_live", float(len(routable)))
        m.set("serve_pool_replicas_total", float(len(self.replicas)))
        m.set("serve_pool_boot_cost_s", self._next_boot_cost_s())
        occs = []
        for r in self.replicas:
            occ = r.occupancy() if r.live else 0.0
            m.set("serve_pool_occupancy", occ, replica=str(r.idx))
            m.set("serve_pool_rung",
                  float(r.rung()) if r.live else 0.0,
                  replica=str(r.idx))
            if r.routable():
                occs.append(occ)
        m.set("serve_pool_occupancy_mean",
              sum(occs) / len(occs) if occs else 0.0)
        m.set("serve_pool_queue_depth",
              float(sum(r.queue_depth() for r in self.replicas
                        if r.live)))
        w0 = t_now - self.window_s
        # full filter, not a sorted-head prune: first-token stamps land
        # in FINISH order and replica clocks interleave, so neither
        # deque is time-sorted — a head-only prune would let stale
        # samples behind an in-window head pollute the p99 gauges.
        # t_now only moves forward, so dropped entries never return.
        self._w_first = deque(x for x in self._w_first if x[0] >= w0)
        self._w_done = deque(x for x in self._w_done if x[0] >= w0)
        ttfts = sorted(v for _t, v in self._w_first)
        tpots = sorted(tp for _t, tp, _n in self._w_done if tp > 0)
        m.set("serve_pool_ttft_p99_window_s", pct(ttfts, 99))
        m.set("serve_pool_tpot_p99_window_s", pct(tpots, 99))
        toks = sum(n for _, _, n in self._w_done)
        m.set("serve_pool_decode_tokens_per_s_window",
              toks / self.window_s if self.window_s > 0 else 0.0)
        # cumulative SLO attainment over the exported error-budget
        # counters — the gauge tools/perf_report.py and slo_report
        # read (1.0 until any request enters the denominator)
        tot = m.counter("serve_slo_requests_total")
        viol = m.counter("serve_slo_violations_total")
        m.set("serve_pool_slo_attainment",
              (tot - viol) / tot if tot > 0 else 1.0)

    def _next_boot_cost_s(self) -> float:
        """Priced cost (seconds of compile) of the NEXT scale-up,
        exported as serve_pool_boot_cost_s: 0 when a parked warm
        replica exists or the ProgramRegistry snapshot in
        --program-cache-dir covers this engine fingerprint (the boot
        deserializes instead of compiling); otherwise the measured
        compile seconds of the most recent cold boot — the compile
        storm made planning-visible instead of an invisible p99
        cliff."""
        if any(not r.live for r in self.replicas):
            return 0.0
        eng = self.replicas[0].engine
        reg = getattr(eng, "programs", None)
        if reg is not None and reg.cache_dir \
                and os.path.exists(reg._store_path()):
            return 0.0
        if self._last_boot and not self._last_boot.get("warm"):
            cs = float(self._last_boot.get("compile_s", 0.0))
            if cs > 0:
                return cs
        bs = getattr(eng, "boot_stats", None) or {}
        return float(bs.get("compile_s", 0.0))

    def _default_autoscaler(self) -> Autoscaler:
        """The --autoscale autoscaler: SLOs/ceiling from FFConfig,
        evaluation cadence and cooldown scaled off the priced step,
        per-replica capacity from the placement search's decode table
        when the cost stack can price this arch."""
        price = self.price_probe(64)
        eng = self.replicas[0].engine
        table = None
        mesh_table = None
        kw = {}
        if self.mesh_placement is not None:
            # the 2-D search already priced the full (t, r) grid —
            # target pricing reads THAT table, so scale decisions and
            # the booted placement agree on one price; the ceiling
            # covers the searched count (2x, the from_config default
            # shape)
            mesh_table = self.mesh_placement.table
            table = self.mesh_placement.decode_by_degree
            kw["max_replicas"] = max(
                2 * self.mesh_placement.replicas,
                int(getattr(self.config, "serve_autoscale_max", 0)))
        else:
            try:
                from ..search.serve_place import optimize_serve
                table = optimize_serve(
                    eng.serve_arch(), max(1, eng.tp),
                    config=self.config).decode_by_degree
            except Exception:
                pass  # unpriceable arch: pure SLO/occupancy triggers
        return Autoscaler.from_config(
            self.config, self.metrics, interval_s=20.0 * price,
            cooldown_s=40.0 * price, decode_table=table,
            mesh_table=mesh_table,
            tensor_parallel=max(1, eng.tp),
            decode_lanes=int(getattr(self.config, "serve_max_seqs",
                                     8)), **kw)

    def _maybe_park(self, r: Replica) -> None:
        """A draining replica parks (warm, routable again on the next
        scale-up) the moment its session empties — checked after
        every step AND at run end, since the last request can finish
        on a dispatched step that is never followed by an empty
        one."""
        if r.draining and not r.session.has_work():
            r.draining = False
            r.live = False

    def _apply_scale(self, decision: Optional[dict], t_now: float
                     ) -> None:
        if decision is None:
            return
        tel = self.telemetry
        w0 = time.perf_counter()
        if decision["direction"] == "up":
            r = self._activate_replica(t_now)
            self.stats["scale_ups"] += 1
        else:
            candidates = [x for x in self.routable()]
            # retire the least-loaded replica (its inflight work
            # drains before it parks)
            r = min(candidates, key=lambda x: (x.occupancy(),
                                               x.queue_depth(),
                                               len(x.inflight),
                                               -x.idx))
            r.draining = True
            # an ALREADY-idle replica parks right here — it will never
            # be stepped again, and a stranded live+draining replica
            # would make the next scale-up build a cold engine while a
            # warm one sits unroutable
            self._maybe_park(r)
            self.stats["scale_downs"] += 1
        event = {**{k: v for k, v in decision.items()},
                 "replica": r.idx}
        self.scale_events.append(event)
        self.metrics.inc("serve_autoscale_events_total",
                         direction=decision["direction"])
        if tel.enabled:
            # the scale event is a SPAN: real wall time spent applying
            # it, virtual decision time in the args. A scale-up's boot
            # cost is carried by the adjacent `replica_boot` span
            # (_activate_replica): warm boots — a parked replica or a
            # --program-cache-dir deserialization — are hairline,
            # and a cold boot's width IS the measured compile storm
            # the autoscaler priced into the decision as `boot_s`
            tel.span(_SCALER_TRACK,
                     f"scale_{decision['direction']}", w0,
                     time.perf_counter(),
                     args={"replica": r.idx, "t_virtual": t_now,
                           "reason": decision["reason"],
                           "live": len(self.routable()),
                           "boot": self._last_boot
                           if decision["direction"] == "up" else None,
                           "priced_target":
                               decision.get("priced_target")})

    def _default_slo_monitor(self, slo_ttft_s, slo_tpot_s
                             ) -> "object":
        """The auto-armed burn-rate monitor (utils/slo.py): windows
        and cadence scaled off the priced virtual step exactly like
        the autoscaler's, error budget from FFConfig.slo_error_budget
        — a deterministic function of the exported counters, so its
        alert transitions replay at one seed."""
        from ..utils.slo import SLOBurnMonitor
        price = self.price_probe(64)
        interval = 20.0 * price
        return SLOBurnMonitor(
            self.metrics,
            error_budget=float(getattr(self.config, "slo_error_budget",
                                       0.01)),
            fast_window_s=5.0 * interval,
            slow_window_s=20.0 * interval,
            interval_s=interval,
            telemetry=self.telemetry,
            slo={"ttft_s": slo_ttft_s or 0.0,
                 "tpot_s": slo_tpot_s or 0.0})

    def run(self, traffic: Sequence[TrafficRequest], *,
            slo_ttft_s: Optional[float] = None,
            slo_tpot_s: Optional[float] = None,
            eos_token: Optional[int] = None,
            autoscaler: Optional[Autoscaler] = None,
            slo_monitor=None,
            sample_seed: int = 0, on_step=None,
            wall_clock: Optional[bool] = None,
            wall_threads: bool = True,
            time_scale: float = 1.0,
            dwell_s: float = 0.0) -> dict:
        """Serve a timed traffic stream and return the
        goodput-under-SLO accounting (also stashed on ``last_stats``).

        Two clocks (docs/serving.md "Wall-clock mode"). The default
        VIRTUAL mode prices each step with the cost stack and replays
        deterministically at one seed — authoritative for search
        A/Bs and autoscaler replay. ``wall_clock=True`` (or
        ``--wall-clock``) serves the SAME traffic in real time:
        arrivals pace on the wall clock (``tr.t_arrival * time_scale``
        seconds after run start) and each replica runs its session
        step loop on its own worker thread (``wall_threads=False``
        steps them round-robin from one thread — the A/B baseline),
        so goodput-under-SLO becomes a measured wall number. TOKENS
        are identical across all modes: sampling keys on stream ids,
        never on the clock. ``dwell_s`` enforces a minimum wall
        duration per dispatched step — the device-dwell stand-in for
        CPU-inline hosts, where XLA "device" time is host time and
        the overlap a real accelerator exposes has nothing to hide
        behind.

        Virtual event loop: the next event is the earlier of (the next
        arrival, the busy replica with the smallest clock). Arrivals
        route + submit (an idle target's clock jumps to the arrival
        instant); a replica step advances its clock by the priced
        step time and stamps first-token/finish times at the step's
        END. The autoscaler (when given) ticks every ``interval_s``
        of virtual time off the freshly exported gauges.
        ``on_step(replica, ev)`` observes every replica step (the
        chaos tests' cluster-wide invariant hook; called from the
        router thread in every mode)."""
        if slo_ttft_s is None:
            ms = float(getattr(self.config, "slo_ttft_ms", 0.0))
            slo_ttft_s = ms / 1e3 if ms > 0 else None
        if slo_tpot_s is None:
            ms = float(getattr(self.config, "slo_tpot_ms", 0.0))
            slo_tpot_s = ms / 1e3 if ms > 0 else None
        if wall_clock is None:
            wall_clock = bool(getattr(self.config, "serve_wall_clock",
                                      False))
        if wall_clock:
            if autoscaler is not None or bool(
                    getattr(self.config, "serve_autoscale", False)):
                raise ValueError(
                    "the autoscaler replays on the virtual clock "
                    "only (its decisions must be reproducible at one "
                    "seed) — run wall-clock without --autoscale")
            return self._run_wall(
                traffic, slo_ttft_s=slo_ttft_s,
                slo_tpot_s=slo_tpot_s, eos_token=eos_token,
                slo_monitor=slo_monitor, sample_seed=sample_seed,
                on_step=on_step, threaded=bool(wall_threads),
                time_scale=float(time_scale), dwell_s=float(dwell_s))
        self._clock = "virtual"
        if autoscaler is None and bool(getattr(self.config,
                                               "serve_autoscale",
                                               False)):
            # --autoscale: arm the config-built autoscaler (SLOs and
            # ceiling from the flags, cadence off the priced step,
            # capacity off the placement search's decode table)
            autoscaler = self._default_autoscaler()
        # slo_monitor=False disarms explicitly (the call-level spelling
        # of FFConfig.slo_monitor=False); None = auto-arm with the SLOs
        arm_default = slo_monitor is None
        if not slo_monitor:
            slo_monitor = None
        if arm_default and (slo_ttft_s or slo_tpot_s) \
                and bool(getattr(self.config, "slo_monitor", True)):
            # burn-rate monitoring comes with the SLOs: a tier with
            # latency targets but no budget alarm is flying blind
            slo_monitor = self._default_slo_monitor(slo_ttft_s,
                                                    slo_tpot_s)
        self._sample_seed = int(sample_seed)
        self._records = {}
        self._req_refs = {}
        self._w_first.clear()
        self._w_done.clear()
        # per-run accounting: self.stats/scale_events stay LIFETIME
        # (the DisaggCluster idiom) and last_stats reports this run's
        # DELTA/slice; round-robin placement restarts so a reused
        # pool reproduces a fresh pool's routing exactly
        stats0 = dict(self.stats)
        events0 = len(self.scale_events)
        self._rr_next = 0
        # fresh per-run sessions on drained replicas: stats_dict (and
        # with it the end-of-run registry fold) must cover THIS run —
        # re-folding a session-lifetime dict would double-count every
        # earlier run's requests. Engine state (prefix cache, compiled
        # programs) persists; only the scheduler/stats reset.
        for r in self.replicas:
            if r.session.reqs and not r.session.has_work():
                r.session.close()
                r.session = r.engine.start_session()
        n_start = len(self.routable())
        arrivals = sorted(traffic,
                          key=lambda r: (r.t_arrival, r.stream_id))
        t0_virtual = arrivals[0].t_arrival if arrivals else 0.0
        if autoscaler is not None:
            self.window_s = max(self.window_s,
                                2.0 * autoscaler.interval_s)
            self._next_eval = t0_virtual + autoscaler.interval_s
        next_slo = (t0_virtual + slo_monitor.interval_s
                    if slo_monitor is not None else None)
        i = 0
        t_virtual = t0_virtual
        while True:
            busy = [r for r in self.replicas if r.has_work()]
            nxt = arrivals[i] if i < len(arrivals) else None
            if not busy and nxt is None:
                break
            step_r = min(busy, key=lambda r: (r.clock_s, r.idx)) \
                if busy else None
            if nxt is not None and (step_r is None
                                    or nxt.t_arrival
                                    <= step_r.clock_s):
                t_virtual = max(t_virtual, nxt.t_arrival)
                self.submit(nxt, eos_token=eos_token)
                i += 1
            else:
                r = step_r
                try:
                    ev = r.session.step()
                except Exception:
                    # contain exactly as generate() would: fail the
                    # in-flight requests, keep the REST of the pool
                    # serving, reopen the replica's session
                    r.engine._fail_inflight(r.session.sched,
                                            r.session.reqs)
                    r.session.close()
                    self._sweep_terminal(r, r.clock_s, slo_ttft_s,
                                         slo_tpot_s)
                    r.session = r.engine.start_session()
                    continue
                if ev is None:
                    self._sweep_terminal(r, r.clock_s, slo_ttft_s,
                                         slo_tpot_s)
                    self._maybe_park(r)
                    continue
                if not ev.dispatched:
                    r._plan_only += 1
                    if r._plan_only > _MAX_PLAN_ONLY:
                        raise RuntimeError(
                            f"replica{r.idx} re-planned "
                            f"{_MAX_PLAN_ONLY} steps without "
                            f"dispatching — scheduler wedged")
                    self._sweep_terminal(r, r.clock_s, slo_ttft_s,
                                         slo_tpot_s)
                    continue
                r._plan_only = 0
                # the priced host-tier DMA rides the same virtual
                # clock the step does: a reload is not free, it is
                # host_transfer seconds the admission already judged
                # cheaper than recompute (engine._host_reload)
                price = self._price(r, ev) + ev.host_reload_s
                r.clock_s += price
                r.busy_s += price
                r.steps += 1
                r.peak_occupancy = max(r.peak_occupancy,
                                       r.occupancy())
                t_end = r.clock_s
                t_virtual = max(t_virtual, t_end)
                for req, n in ev.emitted:
                    tracked = self._inflight.get(req.stream_id)
                    if tracked is None:
                        continue
                    if tracked["tokens_emitted"] == 0:
                        tracked["t_first"] = t_end
                    tracked["tokens_emitted"] += n
                    r.tokens += n
                    ca = tracked["cancel_after"]
                    if ca is not None and not tracked["cancel_sent"] \
                            and tracked["tokens_emitted"] >= ca:
                        # mid-generation abandon: the ONE cancel path
                        # (aborts at the next chunk boundary, pin
                        # reclaims now)
                        self.cancel(req.stream_id)
                self._sweep_terminal(r, t_end, slo_ttft_s, slo_tpot_s)
                self._maybe_park(r)
                if on_step is not None:
                    on_step(r, ev)
            if autoscaler is not None:
                while t_virtual >= self._next_eval:
                    self._export_gauges(self._next_eval)
                    self._apply_scale(
                        autoscaler.evaluate(self._next_eval),
                        self._next_eval)
                    self._next_eval += autoscaler.interval_s
            if slo_monitor is not None:
                # the burn monitor ticks on the same virtual clock the
                # autoscaler does — its counters are kept current by
                # _finalize, so each tick is a pure function of the
                # exported registry + monitor state (replayable)
                while t_virtual >= next_slo:
                    slo_monitor.observe(next_slo)
                    next_slo += slo_monitor.interval_s
        # anything still tracked (a cancel that raced completion)
        for sid in list(self._inflight):
            self._finalize(self._inflight[sid], t_virtual,
                           slo_ttft_s, slo_tpot_s)
        for r in self.replicas:
            self._maybe_park(r)
        self._export_gauges(t_virtual)
        if slo_monitor is not None:
            # one closing tick + episode close, so an alert burning at
            # drain still transitions (and its span gets an end)
            slo_monitor.observe(t_virtual)
            slo_monitor.finish(t_virtual)
        records = [self._records[sid]
                   for sid in sorted(self._records)]
        makespan = max(1e-12, t_virtual - t0_virtual)
        ok = sum(1 for rec in records if rec["slo_ok"])
        completed = sum(1 for rec in records
                        if rec["outcome"] == RequestOutcome.COMPLETED)
        # fold each replica's session stats into the registry — the
        # per-replica LABELED split (the serve_metrics replica= fold,
        # same no-double-counting rule as disagg's roles) plus the
        # unlabeled pool aggregate
        for r in self.replicas:
            st = r.session.stats_dict()
            serve_metrics(st, registry=self.metrics)
            serve_metrics(st, registry=self.metrics,
                          replica=str(r.idx))
        self.last_stats = {
            "mode": "router",
            "policy": self.policy,
            "autoscaled": autoscaler is not None,
            "replicas_start": n_start,
            "replicas_end": len(self.routable()),
            "replicas_total": len(self.replicas),
            "requests": records,
            "goodput_per_s": ok / makespan,
            "slo_attainment": ok / len(records) if records else 0.0,
            "slo_ttft_s": slo_ttft_s, "slo_tpot_s": slo_tpot_s,
            "makespan_s": makespan,
            "completed": completed,
            "slo_ok": ok,
            "cancelled": sum(
                1 for rec in records
                if rec["outcome"] == RequestOutcome.CANCELLED),
            "tokens_total": sum(len(rec["tokens"])
                                for rec in records),
            "routing": {k: self.stats[k] - stats0[k]
                        for k in self.stats},
            "host_tier": self._host_tier_block(),
            "mesh_placement": self._mesh_block(),
            "scale_events": list(self.scale_events[events0:]),
            "per_replica": [
                {"replica": r.idx, "live": r.live,
                 "assigned": r.assigned, "steps": r.steps,
                 "tokens": r.tokens,
                 "busy_virtual_s": r.busy_s,
                 "peak_occupancy": r.peak_occupancy}
                for r in self.replicas],
            "slo_attainment_budget": self.metrics.gauge(
                "serve_pool_slo_attainment", 1.0),
            "slo_alerts": (list(slo_monitor.events)
                           if slo_monitor is not None else []),
        }
        if self.telemetry.enabled:
            # pool-level aggregate latency attribution: every finished
            # request's span fold lands in the shared registry
            # (serve_latency_attribution_* series) and the
            # per-component WALL totals ride along in last_stats
            self.last_stats["attribution"] = self.fold_attribution()
        return self.last_stats

    # ---------------- wall-clock serving --------------------------------
    def _wall_apply(self, r: Replica, ev, t_end: float, busy: float,
                    slo_ttft_s, slo_tpot_s, on_step) -> None:
        """Apply one replica step's outcome to the pool's tracking
        state. Wall mode's single mutation point for router state:
        workers only step sessions and report here, so first-token
        stamps, cancels, finalization, and ``on_step`` all happen on
        the router thread — same ordering discipline as the virtual
        loop, just fed from a queue."""
        if ev is None:
            self._sweep_terminal(r, t_end, slo_ttft_s, slo_tpot_s)
            self._maybe_park(r)
            return
        if not ev.dispatched:
            r._plan_only += 1
            if r._plan_only > _MAX_PLAN_ONLY:
                raise RuntimeError(
                    f"replica{r.idx} re-planned {_MAX_PLAN_ONLY} "
                    f"steps without dispatching — scheduler wedged")
            self._sweep_terminal(r, t_end, slo_ttft_s, slo_tpot_s)
            return
        r._plan_only = 0
        r.busy_wall_s += busy
        r.steps += 1
        r.peak_occupancy = max(r.peak_occupancy, r.occupancy())
        for req, n in ev.emitted:
            tracked = self._inflight.get(req.stream_id)
            if tracked is None:
                continue
            if tracked["tokens_emitted"] == 0:
                tracked["t_first"] = t_end
            tracked["tokens_emitted"] += n
            r.tokens += n
            ca = tracked["cancel_after"]
            if ca is not None and not tracked["cancel_sent"] \
                    and tracked["tokens_emitted"] >= ca:
                # engine.cancel is thread-safe by contract (the worker
                # may be mid-step); the abort lands at the request's
                # next chunk boundary exactly as in virtual mode
                self.cancel(req.stream_id)
        self._sweep_terminal(r, t_end, slo_ttft_s, slo_tpot_s)
        self._maybe_park(r)
        if on_step is not None:
            on_step(r, ev)

    def _wall_step(self, r: Replica, w_start: float, dwell_s: float):
        """One locked session step + the device-dwell floor, returning
        ``(kind, ev, t_end, busy_s)``. The dwell sleep happens OUTSIDE
        the lock: it models time the host is blocked on the device,
        during which the router may submit into this replica."""
        t0 = time.perf_counter()
        with r.lock:
            try:
                ev = r.session.step()
            except Exception:
                # contain exactly as the virtual loop: fail the
                # in-flight requests, reopen the session, keep the
                # rest of the pool serving
                r.engine._fail_inflight(r.session.sched,
                                        r.session.reqs)
                r.session.close()
                r.session = r.engine.start_session()
                return ("fail", None,
                        time.perf_counter() - w_start, 0.0)
        elapsed = time.perf_counter() - t0
        if ev is not None and ev.dispatched and dwell_s > elapsed:
            time.sleep(dwell_s - elapsed)
            elapsed = dwell_s
        return ("step", ev, time.perf_counter() - w_start, elapsed)

    def _run_wall(self, traffic: Sequence[TrafficRequest], *,
                  slo_ttft_s, slo_tpot_s, eos_token, slo_monitor,
                  sample_seed, on_step, threaded: bool,
                  time_scale: float, dwell_s: float) -> dict:
        """Serve the traffic stream in real time (docs/serving.md
        "Wall-clock mode"). Arrivals pace on the wall clock —
        request i submits ``(t_arrival - t0) * time_scale`` wall
        seconds after run start — and timestamps (t_arrival, t_first,
        t_finish) are run-relative wall seconds on ONE clock, so
        ``explain_request`` still sums exactly to measured latency.

        ``threaded=True``: each replica's session step loop runs on
        its own worker thread; the worker holds ``replica.lock``
        across ``session.step()`` (the router thread holds it across
        ``session.submit()``) and reports completed steps into a
        queue the router thread drains — all router state mutates on
        the router thread. ``threaded=False`` steps busy replicas
        round-robin from the router thread: the A/B baseline the
        fabric bench's >= 1.3x goodput gate divides by.

        No autoscaler here (it replays on the virtual clock), and no
        auto-armed SLO monitor — pass one explicitly to tick it on
        wall time. Tokens are identical to the virtual run at the
        same seed: sampling keys on stream ids, never on the
        clock."""
        slo_monitor = slo_monitor or None
        self._sample_seed = int(sample_seed)
        self._records = {}
        self._req_refs = {}
        self._w_first.clear()
        self._w_done.clear()
        stats0 = dict(self.stats)
        events0 = len(self.scale_events)
        self._rr_next = 0
        for r in self.replicas:
            if r.session.reqs and not r.session.has_work():
                r.session.close()
                r.session = r.engine.start_session()
        n_start = len(self.routable())
        arrivals = sorted(traffic,
                          key=lambda r: (r.t_arrival, r.stream_id))
        t0_virtual = arrivals[0].t_arrival if arrivals else 0.0
        sched = [(tr.t_arrival - t0_virtual) * time_scale
                 for tr in arrivals]
        self._clock = "wall"
        done_q: "queue.Queue" = queue.Queue()
        stop = threading.Event()
        wakes = [threading.Event() for _ in self.replicas]
        workers: List[threading.Thread] = []
        w_start = time.perf_counter()

        def _worker(r: Replica, wake: threading.Event) -> None:
            while not stop.is_set():
                if not r.has_work():
                    wake.wait(0.005)
                    wake.clear()
                    continue
                kind, ev, t_end, busy = self._wall_step(
                    r, w_start, dwell_s)
                done_q.put((kind, r.idx, ev, t_end, busy))

        try:
            if threaded:
                for r, wake in zip(self.replicas, wakes):
                    t = threading.Thread(
                        target=_worker, args=(r, wake),
                        name=f"replica{r.idx}-step", daemon=True)
                    t.start()
                    workers.append(t)
            next_slo = (slo_monitor.interval_s
                        if slo_monitor is not None else None)
            i = 0
            rr = 0
            t_now = 0.0
            last_progress = time.perf_counter()
            while True:
                t_now = time.perf_counter() - w_start
                while i < len(arrivals) and sched[i] <= t_now + 1e-9:
                    tr = arrivals[i]
                    # submit holds EVERY replica lock (idx order):
                    # route() reads all replicas' queue/cache state
                    # and session.submit mutates the winner — both
                    # must not interleave with a worker's step
                    for r in self.replicas:
                        r.lock.acquire()
                    try:
                        tracked = self.submit(tr, eos_token=eos_token)
                    finally:
                        for r in reversed(self.replicas):
                            r.lock.release()
                    # SLOs measure from the SCHEDULED wall arrival —
                    # router lag between the pacer and submit() is
                    # queueing delay the tier must answer for
                    tracked["t_arrival"] = sched[i]
                    if threaded:
                        wakes[tracked["replica"]].set()
                    i += 1
                    last_progress = time.perf_counter()
                if i >= len(arrivals) and not self._inflight:
                    break
                if threaded:
                    timeout = 0.05 if i >= len(arrivals) else \
                        min(0.05, max(0.0, sched[i] - t_now))
                    try:
                        item = done_q.get(timeout=timeout) \
                            if timeout > 0 else done_q.get_nowait()
                    except queue.Empty:
                        if i >= len(arrivals) \
                                and not any(r.has_work()
                                            for r in self.replicas):
                            break  # drained: a raced cancel's record
                        if time.perf_counter() - last_progress > 60.0:
                            raise RuntimeError(
                                "wall-clock pool made no progress "
                                "for 60s with work pending")
                        continue
                    while item is not None:
                        kind, idx, ev, t_end, busy = item
                        r = self.replicas[idx]
                        if kind == "fail":
                            self._sweep_terminal(r, t_end, slo_ttft_s,
                                                 slo_tpot_s)
                        else:
                            self._wall_apply(r, ev, t_end, busy,
                                             slo_ttft_s, slo_tpot_s,
                                             on_step)
                        last_progress = time.perf_counter()
                        try:
                            item = done_q.get_nowait()
                        except queue.Empty:
                            item = None
                else:
                    busy_rs = [r for r in self.replicas
                               if r.has_work()]
                    if not busy_rs:
                        if i < len(arrivals):
                            time.sleep(
                                min(0.05,
                                    max(0.0, sched[i] - t_now)))
                            continue
                        break  # drained: a raced cancel's record
                    r = busy_rs[rr % len(busy_rs)]
                    rr += 1
                    kind, ev, t_end, busy = self._wall_step(
                        r, w_start, dwell_s)
                    if kind == "fail":
                        self._sweep_terminal(r, t_end, slo_ttft_s,
                                             slo_tpot_s)
                    else:
                        self._wall_apply(r, ev, t_end, busy,
                                         slo_ttft_s, slo_tpot_s,
                                         on_step)
                    last_progress = time.perf_counter()
                if slo_monitor is not None:
                    t_now = time.perf_counter() - w_start
                    while t_now >= next_slo:
                        slo_monitor.observe(next_slo)
                        next_slo += slo_monitor.interval_s
        finally:
            stop.set()
            for wake in wakes:
                wake.set()
            for t in workers:
                t.join(timeout=5.0)
            self._clock = "virtual"
        t_final = time.perf_counter() - w_start
        # drain-time finalization still belongs to the wall run (the
        # finally above restored the label for the exception paths)
        self._clock = "wall"
        for sid in list(self._inflight):
            self._finalize(self._inflight[sid], t_final, slo_ttft_s,
                           slo_tpot_s)
        for r in self.replicas:
            self._maybe_park(r)
        self._export_gauges(t_final)
        self._clock = "virtual"
        if slo_monitor is not None:
            slo_monitor.observe(t_final)
            slo_monitor.finish(t_final)
        records = [self._records[sid]
                   for sid in sorted(self._records)]
        makespan = max(1e-12, t_final)
        ok = sum(1 for rec in records if rec["slo_ok"])
        completed = sum(1 for rec in records
                        if rec["outcome"] == RequestOutcome.COMPLETED)
        for r in self.replicas:
            st = r.session.stats_dict()
            serve_metrics(st, registry=self.metrics)
            serve_metrics(st, registry=self.metrics,
                          replica=str(r.idx))
        self.last_stats = {
            "mode": "router",
            "clock": "wall",
            "wall_threads": threaded,
            "time_scale": time_scale,
            "dwell_s": dwell_s,
            "policy": self.policy,
            "autoscaled": False,
            "replicas_start": n_start,
            "replicas_end": len(self.routable()),
            "replicas_total": len(self.replicas),
            "requests": records,
            "goodput_per_s": ok / makespan,
            "slo_attainment": ok / len(records) if records else 0.0,
            "slo_ttft_s": slo_ttft_s, "slo_tpot_s": slo_tpot_s,
            "makespan_s": makespan,
            "completed": completed,
            "slo_ok": ok,
            "cancelled": sum(
                1 for rec in records
                if rec["outcome"] == RequestOutcome.CANCELLED),
            "tokens_total": sum(len(rec["tokens"])
                                for rec in records),
            "routing": {k: self.stats[k] - stats0[k]
                        for k in self.stats},
            "host_tier": self._host_tier_block(),
            "mesh_placement": self._mesh_block(),
            "scale_events": list(self.scale_events[events0:]),
            "per_replica": [
                {"replica": r.idx, "live": r.live,
                 "assigned": r.assigned, "steps": r.steps,
                 "tokens": r.tokens,
                 "busy_virtual_s": r.busy_s,
                 "busy_wall_s": r.busy_wall_s,
                 "peak_occupancy": r.peak_occupancy}
                for r in self.replicas],
            "slo_attainment_budget": self.metrics.gauge(
                "serve_pool_slo_attainment", 1.0),
            "slo_alerts": (list(slo_monitor.events)
                           if slo_monitor is not None else []),
        }
        if self.telemetry.enabled:
            self.last_stats["attribution"] = self.fold_attribution()
        return self.last_stats

    # ---------------- per-request observability -------------------------
    def explain_request(self, stream_id: int) -> dict:
        """Cross-engine latency attribution for one routed request of
        the last run, by stream id (docs/observability.md): the trace
        id minted at submit ties the router's routing span, the
        replica's queue_wait, its prefill/decode chunk spans and any
        preempt/retry stalls into one additive WALL-clock breakdown
        summing to the request's measured wall latency. (The virtual-
        clock TTFT/TPOT in last_stats price the simulated cluster;
        this explains where the real host/device time went.)"""
        if not self.telemetry.enabled:
            raise RuntimeError(
                "explain_request needs telemetry (pass telemetry= or "
                "set --telemetry/--trace-out)")
        req = self._req_refs.get(stream_id)
        if req is None:
            raise KeyError(
                f"stream id {stream_id} has no finalized request in "
                f"the last run")
        if not req.t_finish:
            raise ValueError(
                f"stream {stream_id} never terminated (outcome "
                f"{req.outcome!r})")
        out = self.telemetry.explain_request(
            req.trace_id, req.t_submit, req.t_finish)
        rec = self._records.get(stream_id) or {}
        out.update(stream_id=stream_id, outcome=req.outcome,
                   replica=rec.get("replica"),
                   tokens=len(req.out_tokens))
        return out

    def fold_attribution(self, registry=None) -> dict:
        """Fold every terminated request of the last run into
        `registry` (default: the pool registry) — the pool-level
        aggregate `serve_latency_attribution_*` series. Returns the
        per-component second totals."""
        from ..utils.telemetry import (REQUEST_COMPONENTS,
                                       fold_attribution)
        m = registry if registry is not None else self.metrics
        totals = {c: 0.0 for c in REQUEST_COMPONENTS}
        if not self.telemetry.enabled:
            return totals
        for sid in sorted(self._req_refs):
            req = self._req_refs[sid]
            if not req.t_finish:
                continue
            b = self.telemetry.explain_request(
                req.trace_id, req.t_submit, req.t_finish)
            fold_attribution(b, m)
            for c, v in b["components"].items():
                totals[c] += v
        return totals

    def dump_postmortem(self, path: Optional[str] = None,
                        reason: str = "manual",
                        detail: Optional[dict] = None) -> str:
        """Pool flight-recorder dump: the lead replica engine's bundle
        (the replicas share ONE telemetry bus, so its ring/metrics ARE
        the tier's) plus the router's routing/scale state and every
        replica's scheduler + KV-pool snapshot."""
        from ..utils.telemetry import write_json_atomic
        lead = self.replicas[0].engine
        bundle = lead.postmortem_bundle(
            reason, detail, sched=self.replicas[0].session.sched)
        bundle["mode"] = "router"
        bundle["router"] = {
            "policy": self.policy,
            "stats": dict(self.stats),
            "inflight": len(self._inflight),
            "scale_events": list(self.scale_events[-32:]),
            "host_tier": (self.host_tier.debug_state()
                          if self.host_tier is not None else None),
        }
        bundle["replicas"] = {
            f"replica{r.idx}": {
                "live": r.live, "draining": r.draining,
                "clock_virtual_s": r.clock_s,
                "scheduler": r.session.sched.debug_state(),
                "kv_pool": r.engine.cache.debug_state(),
                "compile_counts": r.engine.compile_counts(),
            } for r in self.replicas}
        if path is None:
            path = lead._postmortem_path(reason)
        return write_json_atomic(path, bundle)
