"""Host-RAM tier below the HBM page pool (hierarchical prefix cache).

`HostPageStore` holds spilled KV pages as host numpy bytes in the KV
storage dtype plus the f32 scale rows — exactly the per-page layout
`ServeEngine.export_kv` produces — keyed by the same chain-hash page
keys the HBM prefix registry uses. Instead of a refcount-0 hashed page
under pressure being discarded (its prefix recomputed from tokens),
`PagedKVCache` queues its identity here and the engine DMAs the bytes
out through the existing fixed-shape export program; a later prefix
match re-imports through the fixed-shape import scatter — zero new
compiles either way.

The store is byte-budgeted (`--host-tier-mb`) with its own LRU, and is
shared: `ReplicaPool` builds ONE store for every replica so a tenant's
preamble crosses HBM once per replica instead of once per request. The
wall-clock fabric steps replicas on worker threads, so every method
takes the store lock.

Whether a host hit is worth reloading at all is NOT decided here — the
scheduler prices DMA-vs-recompute per chunk through
`TPUMachineModel.host_transfer` (see ServeEngine._host_reload); the
store only answers "which keys do I hold".
"""

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import threading

import numpy as np


class HostPageStore:
    """Byte-budgeted host-RAM LRU of spilled KV pages, chain-key keyed.

    Each entry is the tuple of per-pool page rows export_kv yields for
    one page: `(k, v)` at the storage dtype for unquantized pools, or
    `(k, v, k_scale, v_scale)` with f32 scale rows for int8/fp8 pools
    (shapes `(num_layers, page_size, num_heads, head_dim)` for values,
    minus `head_dim` for scales). The first `put` pins the geometry
    signature (shapes + dtypes); mismatching entries are rejected so a
    shared store can never hand a replica rows its import program
    cannot scatter (replicas in a pool share one model geometry).
    """

    def __init__(self, budget_mb: float = 256.0):
        if budget_mb <= 0:
            raise ValueError(f"host tier budget must be > 0 MB "
                             f"(got {budget_mb})")
        self.budget_bytes = int(budget_mb * (1 << 20))
        self._lock = threading.Lock()
        self._pages: "OrderedDict[bytes, Tuple[np.ndarray, ...]]" = \
            OrderedDict()          # key -> per-pool page rows, LRU order
        self._bytes = 0
        self._sig: Optional[Tuple] = None
        self.stats: Dict[str, int] = {
            "spills": 0,       # pages stored (puts accepted)
            "reloads": 0,      # pages handed back for HBM re-import
            "hits": 0,         # keys found by match_chain/contains
            "misses": 0,       # keys probed but absent
            "evictions": 0,    # pages dropped by the byte-budget LRU
            "rejects": 0,      # puts refused (geometry / oversized)
        }

    # ---------------- geometry ----------------------------------------
    @staticmethod
    def _signature(rows: Sequence[np.ndarray]) -> Tuple:
        return tuple((tuple(r.shape), str(r.dtype)) for r in rows)

    @staticmethod
    def _nbytes(rows: Sequence[np.ndarray]) -> int:
        return int(sum(int(r.nbytes) for r in rows))

    # ---------------- writes ------------------------------------------
    def put(self, key: bytes, rows: Sequence[np.ndarray]) -> bool:
        """Store one spilled page's rows under its chain key. Copies
        the rows (callers hand views over export buffers), refreshes
        LRU position on re-put, and evicts from the LRU end until the
        byte budget holds. Returns False when the entry is rejected
        (geometry drift, or a single page larger than the budget)."""
        rows = tuple(np.ascontiguousarray(r) for r in rows)
        sig = self._signature(rows)
        nbytes = self._nbytes(rows)
        with self._lock:
            if self._sig is None:
                self._sig = sig
            elif sig != self._sig:
                self.stats["rejects"] += 1
                return False
            if nbytes > self.budget_bytes:
                self.stats["rejects"] += 1
                return False
            old = self._pages.pop(key, None)
            if old is not None:
                self._bytes -= self._nbytes(old)
            self._pages[key] = rows
            self._bytes += nbytes
            while self._bytes > self.budget_bytes and self._pages:
                _, dropped = self._pages.popitem(last=False)
                self._bytes -= self._nbytes(dropped)
                self.stats["evictions"] += 1
            self.stats["spills"] += 1
            return True

    # ---------------- reads -------------------------------------------
    def get(self, key: bytes) -> Optional[Tuple[np.ndarray, ...]]:
        """The rows for one key (LRU-touched), or None. Counts as a
        reload — callers fetch only when actually re-importing."""
        with self._lock:
            rows = self._pages.get(key)
            if rows is None:
                self.stats["misses"] += 1
                return None
            self._pages.move_to_end(key)
            self.stats["hits"] += 1
            self.stats["reloads"] += 1
            return rows

    def contains(self, key: bytes) -> bool:
        with self._lock:
            return key in self._pages

    def match_chain(self, keys: Sequence[bytes]) -> int:
        """Longest PREFIX run of `keys` resident in the store — the
        host-tier mirror of `PagedKVCache.match_prefix` (chain hashes
        make any gap unmatchable, so only the leading run counts).
        Touches matched keys to MRU; counts one hit/miss per probe."""
        n = 0
        with self._lock:
            for key in keys:
                if key not in self._pages:
                    if n < len(keys):
                        self.stats["misses"] += 1
                    break
                self._pages.move_to_end(key)
                self.stats["hits"] += 1
                n += 1
        return n

    def probe_chain(self, keys: Sequence[bytes]) -> int:
        """Pure observation for the router's affinity probe: the
        longest resident prefix run WITHOUT LRU-touching or stat
        counting — `route()` must not perturb the store (only an
        actual admission-time match should refresh recency)."""
        n = 0
        with self._lock:
            for key in keys:
                if key not in self._pages:
                    break
                n += 1
        return n

    # ---------------- maintenance -------------------------------------
    def discard(self, keys: Sequence[bytes]) -> int:
        """Drop entries (e.g. a pool reset invalidating content).
        Returns the number removed; not counted as budget evictions."""
        removed = 0
        with self._lock:
            for key in keys:
                rows = self._pages.pop(key, None)
                if rows is not None:
                    self._bytes -= self._nbytes(rows)
                    removed += 1
        return removed

    def clear(self) -> None:
        with self._lock:
            self._pages.clear()
            self._bytes = 0

    # ---------------- introspection -----------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._pages)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def report(self) -> Dict[str, object]:
        """The host-tier block of serve stats / reports."""
        with self._lock:
            return {
                "pages": len(self._pages),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "occupancy": (self._bytes / self.budget_bytes
                              if self.budget_bytes else 0.0),
                **{k: int(v) for k, v in self.stats.items()},
            }

    def debug_state(self, max_keys: int = 32) -> Dict[str, object]:
        """Post-mortem view: occupancy plus a bounded LRU-ordered key
        sample (hex, oldest first) so a flight-recorder dump shows what
        was spilled and what the budget was about to drop."""
        with self._lock:
            keys = list(self._pages)
            return {
                "pages": len(keys),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "stats": {k: int(v) for k, v in self.stats.items()},
                "lru_keys": [k.hex()[:16] for k in keys[:max_keys]],
                "lru_truncated": max(0, len(keys) - max_keys),
            }
