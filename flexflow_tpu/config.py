"""Runtime configuration for the TPU-native FlexFlow rebuild.

Mirrors the knob surface of the reference `FFConfig` (reference:
include/config.h:98-154, parse_args src/runtime/model.cc:2258-2379) but
re-targeted at TPU execution: instead of Legion `-ll:*` resource flags the
machine is described by a `jax.sharding.Mesh` (see
:mod:`flexflow_tpu.parallel.mesh`).
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp


class CompMode:
    """Computation mode (reference: ffconst.h COMP_MODE_TRAINING/INFERENCE)."""

    TRAINING = "training"
    INFERENCE = "inference"


class ParameterSyncType:
    """Kept for API compatibility with the reference (ffconst.h:44-48).

    On TPU both modes lower to XLA collectives chosen by GSPMD; `PS` and
    `NCCL` differ only in how the reference moved gradients, which has no
    TPU analog (SURVEY.md section 7, hard part (e)).
    """

    NONE = "none"
    PS = "ps"
    NCCL = "nccl"


# KV-page storage formats the serve stack supports (--kv-dtype). The
# ONE allowlist: serve/kv_cache.py derives its byte accounting from it.
# "float8_e4m3" stores ml_dtypes' e4m3fn pages and reuses the int8
# per-row scale machinery verbatim (serve/kv_cache.kv_storage_dtype).
KV_DTYPES = ("float32", "bfloat16", "int8", "float8_e4m3")


@dataclasses.dataclass
class FFIterationConfig:
    """Per-iteration runtime config (reference: include/config.h:156-161).

    ``seq_length`` truncates sequence-bearing shapes (BatchMatmul /
    attention) for variable-length batches.
    """

    seq_length: int = -1

    def reset(self) -> None:
        self.seq_length = -1


def _int_or_auto(v) -> Union[int, str]:
    """--serve-replicas value parser: an explicit replica count, or
    'auto' to resolve the pool shape through the 2-D serve-mesh
    search (search/serve_place.optimize_serve_mesh)."""
    s = str(v).strip()
    return "auto" if s == "auto" else int(s)


@dataclasses.dataclass
class FFConfig:
    """All runtime knobs.

    Reference parity (include/config.h:98-154):
      batchSize -> batch_size, epochs -> epochs, iterations -> iterations,
      numNodes/workersPerNode -> described by the mesh,
      learningRate/weightDecay -> lr/weight_decay (consumed by optimizers),
      search_budget/search_alpha/search_overlap_backward_sync ->
        search_* (consumed by flexflow_tpu.search.mcmc),
      import_strategy_file/export_strategy_file -> strategy I/O,
      enable_sample_parallel/parameter_parallel/attribute_parallel ->
        search-space gates, plus the new TPU-first axes (sequence/expert/
        pipeline parallel) which the reference lacked (SURVEY.md 2.4).
    """

    batch_size: int = 64
    epochs: int = 1
    iterations: int = 1
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    seed: int = 0

    # numerics — the mixed-precision policy (core/precision.py):
    # `param_dtype` is the MASTER storage dtype of float parameters and
    # optimizer state (f32 by default — the loss-scaling-free bf16
    # recipe keeps f32 masters); `compute_dtype` is the dtype
    # params/activations are cast to INSIDE the jitted step (bf16 runs
    # the MXU at ~2x f32 rate and halves HBM/ICI bytes). Softmax/LSE,
    # losses, metrics, BN/LN statistics and reduction accumulators stay
    # f32 regardless (preferred_element_type — the flash-attention
    # convention). The strategy-search cost stack prices both dtypes
    # (search/machine_model.py, search/cost_model.py).
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    # profiling / debugging
    profiling: bool = False
    log_instance_creation: bool = False
    # jax.profiler trace directory for utils/profiling.trace()
    # (TensorBoard-viewable XLA traces); None = /tmp/flexflow_tpu_trace.
    # --trace-dir.
    trace_dir: Optional[str] = None

    # ---- telemetry (utils/telemetry.py, docs/observability.md) ----
    # structured event bus + metrics registry + simulator-drift
    # calibrator: per-request lifecycle spans in ServeEngine (queue
    # wait, prefill chunks, decode steps, preemption, speculation,
    # retries, degradation rungs, cancel/deadline) and per-step train
    # spans in fit (dispatch, fetch wait), with Chrome-trace and
    # Prometheus-style exporters. Host-side only: telemetry on vs off
    # is token-identical with zero recompiles at <= 3% step-time
    # overhead (ci.sh step 1k). --telemetry enables; --trace-out PATH
    # also enables and writes the Chrome trace-event JSON there
    # (Perfetto / chrome://tracing-loadable) at the end of each
    # generate()/fit().
    telemetry: bool = False
    trace_out: Optional[str] = None
    # bounded event ring-buffer size (ONE deque, oldest spans drop
    # first; metrics/drift aggregates are never dropped)
    telemetry_buffer_events: int = 65536
    # drift_report() flags a regime when measured/predicted leaves
    # [1/(1+thr), 1+thr] — 0.5 means "off by more than 1.5x either way"
    telemetry_drift_threshold: float = 0.5
    # live scrape endpoint (utils/telemetry.MetricsServer): serve
    # /metrics (Prometheus text from the engine's lifetime registry)
    # and /healthz from a stdlib http.server thread. None = off;
    # 0 = bind an ephemeral port (the bound port is on
    # engine.metrics_server.port); N = that port. Setting it also
    # enables telemetry (the registry must be live to scrape). The
    # ROADMAP replica-autoscaler polls this. --metrics-port.
    metrics_port: Optional[int] = None
    # bind address for the scrape endpoint: loopback by default (safe
    # on shared hosts); set "0.0.0.0" to expose it to a pod/host
    # network scraper. --metrics-host.
    metrics_host: str = "127.0.0.1"
    # failure flight recorder (docs/observability.md "Failure flight
    # recorder"): when set, ServeEngine (and the router/disagg tiers
    # above it) auto-dump a bounded post-mortem bundle — last-N ring
    # spans, metrics/drift snapshots, memory ledger, scheduler + KV
    # pool state, fault accounting — into this directory on
    # fault-abort, deadline storm, or rung-4 rejection (atomic
    # tmp+rename; rate-limited; loadable by tools/postmortem.py).
    # Setting it implies telemetry (the bundle needs the span ring).
    # postmortem_events bounds the bundle's event payload.
    # --postmortem-dir / --postmortem-events.
    postmortem_dir: Optional[str] = None
    postmortem_events: int = 2048
    # SLO burn-rate monitor (utils/slo.py, rendered by
    # tools/slo_report.py): the tolerated violation fraction of the
    # slo_ttft_ms/slo_tpot_ms targets (0.01 = a 99% SLO). The
    # ReplicaPool auto-arms the monitor whenever SLO targets are set
    # (slo_monitor=False disarms); alerts fire on fast+slow windowed
    # burn rates over exported counters only, deterministic at one
    # seed. --slo-error-budget / --no-slo-monitor.
    slo_error_budget: float = 0.01
    slo_monitor: bool = True

    # ---- async/overlap training runtime (core/overlap.py) ----
    # bucketed, backward-overlapped gradient sync: the walk's weighted
    # ops partition into contiguous buckets of ~this many MiB of master
    # parameters, and each bucket's data-axis gradient all-reduce is
    # anchored (custom_vjp sync point + optimization_barrier) at the
    # point in the backward pass where the bucket's grads complete, so
    # XLA schedules it concurrently with the remaining backward instead
    # of coalescing one monolithic end-of-backward sync. Gradients are
    # BIT-identical either way (same reduction set, donation
    # preserved). 0 = legacy monolithic sync; None (the default) =
    # AUTO-TUNE from the machine model at compile time
    # (core/overlap.resolve_bucket_mb: interconnect bandwidth x the
    # expected backward slice picks the bucket granularity; resolves to
    # 0 when there is no data axis to sync over). Explicit values are
    # authoritative, and the RESOLVED value is what the cost-cache
    # machine fingerprint folds. --grad-bucket-mb.
    grad_bucket_mb: Optional[float] = None
    # pipelined host dispatch (model.fit): keep up to this many train
    # dispatches in flight before retrieving the oldest step's host
    # metrics — depth 2 retrieves step N while step N+1 runs on device.
    # 1 = fully synchronous (block on every step), 0 = unbounded
    # (epoch-bulk retrieval, device metric handles grow with the
    # epoch). --train-dispatch-depth.
    train_dispatch_depth: int = 2

    # auto-parallelization (reference: config.h:116-141)
    search_budget: int = 0
    search_alpha: float = 0.05
    # simulator overlap modeling (reference search_overlap_backward_
    # update, simulator.cc:393-497): when True (default) gradient-sync
    # tasks may overlap the remaining backward pass — bucket-granular
    # when grad_bucket_mb > 0, per-op otherwise; when False every sync
    # serializes after the whole backward. Folded into the cost-cache
    # machine fingerprint, so flipping it can never resurrect stale
    # entries. --no-overlap-sync disables.
    search_overlap_backward_sync: bool = True
    # delta re-simulation (Simulator.simulate_delta): per proposal,
    # re-cost only the moved op(s) and replay the cached scheduled task
    # graph instead of rebuilding + rescheduling everything — the
    # paper's delta simulation algorithm; exact (bit-equal makespans),
    # with periodic full-simulation re-syncs counted in search stats.
    # --no-delta-sim falls back to full simulation per move.
    search_delta_sim: bool = True
    # parallel annealing chains (Python engine): K independent MCMC
    # walks with per-chain seeds derived from `seed`, splitting the
    # TOTAL budget and sharing one read-mostly cost cache; best chain
    # wins. 0 = auto (min(4, cpu_count)).
    search_chains: int = 0
    # persistent per-op cost cache (search/cost_cache.py): serialize
    # simulator costs keyed by (op signature, axis map, machine-model
    # fingerprint) so repeated searches and mesh-shape sweeps skip
    # re-deriving/re-measuring. cost_cache_file=None uses
    # ~/.cache/flexflow_tpu/costcache.json (FLEXFLOW_TPU_CACHE root).
    search_cost_cache: bool = True
    cost_cache_file: Optional[str] = None
    import_strategy_file: Optional[str] = None
    export_strategy_file: Optional[str] = None
    enable_sample_parallel: bool = True
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    # TPU-first additions: new parallel axes (SURVEY.md section 2.4 calls
    # these out as absent from the reference and required here).
    enable_sequence_parallel: bool = False
    # SP attention lowering: "ring" (K/V rotate over ICI, no score
    # materialization — arbitrary lengths), "alltoall" (heads scatter /
    # seq gathers, full-MXU blocks — needs heads % axis == 0), or
    # "auto" (alltoall when heads divide and the per-device score
    # matrix fits; parallel/ulysses.sp_mode_for)
    sp_attention: str = "auto"
    # ZeRO-1: shard dense optimizer slots (momentum/adam moments) over
    # the `data` mesh axis — pure GSPMD annotations (the slot arrays
    # get a data-sharded NamedSharding and the update constrains them
    # to stay there; XLA inserts the reduce-scatter/all-gather), no
    # manual collectives. Cuts optimizer memory by the DP degree.
    zero_optimizer_sharding: bool = False
    enable_expert_parallel: bool = False
    enable_pipeline_parallel: bool = False
    enable_propagation: bool = False
    # search the mesh factorization (parallel DEGREE) too: 8 devices ->
    # dp8 vs dp4xtp2 vs dp2xtp4 ... (the reference samples ND part counts
    # in get_random_parallel_config, model.cc:512; here the degree comes
    # from the mesh, so the search enumerates mesh shapes).
    search_mesh_shapes: bool = False
    # offer device-explicit placement candidates (__devices__ bindings,
    # reference ParallelConfig.device_ids) to the search. OPT-IN: GSPMD
    # executes such strategies as replication (the executable form of
    # per-table placement is DistributedEmbedding's table sharding), so
    # they are for strategy-space exploration/export tooling.
    enable_device_placement: bool = False
    machine_model_file: Optional[str] = None
    # ground the cost model per-op: the top-N ops by analytic time get
    # their fwd/bwd timed as isolated jitted kernels at the strategy's
    # sub-shape (search/op_measure.py — the analog of the reference
    # measuring every op's real kernels at search time, model.cu:20-62).
    # 0 = analytic-only (default: measuring pays a jit compile per
    # distinct op shape on first use; cached per machine thereafter).
    measure_top_ops: int = 0
    # DOT export of the simulated task graph (reference --taskgraph,
    # simulator.cc:508-556); written by the first simulate() of a search.
    taskgraph_file: Optional[str] = None
    # Perfetto export of the WINNING strategy's simulated event-loop
    # schedule (Simulator.export_schedule): per-resource tracks,
    # critical-path flags, exact makespan metadata — the visual twin
    # of a measured --trace-out trace. Written at the end of optimize.
    # --schedule-trace.
    schedule_trace_file: Optional[str] = None
    # per-proposal search tracing (search/trace.SearchTrace): every
    # MCMC proposal (iteration, chain, op moved, delta-cost,
    # accept/reject, delta-vs-full path) lands in a bounded ring with
    # convergence diagnostics (acceptance by phase, best-cost curve)
    # surfaced in search_report / BENCH_search.json. Pure host-side
    # observation: traced and untraced searches are bit-identical at
    # the same seed. The native C++ walk is untraced (its loop lives
    # in csrc/mcmc.cc): use_native=False gets diagnostics there.
    # --no-search-trace disables.
    search_trace: bool = True

    # MoE dispatch path: "auto" uses dense GShard masks (MXU-friendly,
    # clean EP all-to-alls) until the mask would exceed
    # ops/moe.py DENSE_MASK_ELEMENT_LIMIT elements, then switches to
    # sorted-scatter routing (argsort by expert; no (S, E, C) mask —
    # the scalable form for large expert counts). "dense"/"sorted"
    # force a path.
    moe_dispatch: str = "auto"

    # generalized pipeline parallelism (core/staged.py): auto-cut the op
    # graph into this many flops-balanced stages over a matching mesh
    # axis. 0 = off. Strategy device pins trigger staged execution
    # independently of this knob.
    pipeline_stages: int = 0
    pipeline_microbatches: int = 4
    pipeline_schedule: str = "gpipe"
    # interleaved (virtual-stage) 1F1B: each pipe device hosts this
    # many round-robin stage chunks (Megatron interleaving), dividing
    # the warmup/drain bubble by up to v. Requires
    # pipeline_schedule="1f1b" with auto-cut stages; 1 = off.
    pipeline_virtual_stages: int = 1

    # fusion (reference: --fusion flag, model.cc:1472)
    perform_fusion: bool = False

    # sibling-conv batching: convs that read the SAME tensor with the
    # SAME geometry (the 1x1 branch heads of an Inception module)
    # execute as ONE conv with their kernels concatenated along
    # channel-out, outputs sliced back per branch. Exact numerics (each
    # output channel's contraction is unchanged); the win is MXU lane
    # occupancy — three couts of 192/160/160 pad to 256 lanes each
    # (25-37% waste) where the merged 512 tiles perfectly. No reference
    # analog (cuDNN picks per-conv algorithms instead,
    # conv_2d.cu:173-260); this is the TPU-shaped counterpart.
    sibling_conv_fusion: bool = True

    # remat: trade FLOPs for HBM (no reference analog; TPU-first)
    remat: bool = False

    # compute layout for Conv2D/Pool2D/BatchNorm: "NCHW" (logical, the
    # reference's layout) or "NHWC" (channels on the TPU lane dim; ops
    # transpose at their boundaries and XLA cancels the interior pairs).
    conv_layout: str = "NCHW"

    # multi-step dispatch body: "auto" unrolls the K steps (instead of
    # lax.scan) only when donated params are a large fraction of device
    # memory — a TPU scan carry is double-buffered, so at DLRM scale
    # (26x1M-row tables) the scanned program needs 2x-table scratch and
    # OOMs a chip the unrolled/single-step program fits. True/False
    # force either body.
    multi_step_unroll: object = "auto"

    # sparse embedding updates: when the optimizer's exact rule can be
    # applied row-wise (SGD, no momentum/decay), embedding tables whose
    # index tensors are graph inputs skip the dense-gradient sweep and
    # get a scatter update over the touched rows only (reference analog:
    # scatter-add embedding backward, src/ops/embedding.cu; essential
    # for DLRM-scale vocabularies where a dense step writes GBs).
    sparse_embedding_updates: bool = True

    # opt-in: also use the sparse path when the optimizer only has a
    # LAZY sparse form (SGD+momentum, Adam): touched rows get the exact
    # rule on coalesced gradients, untouched rows keep stale state
    # (momentum does not decay, Adam m/v do not advance) — the
    # torch.optim.SparseAdam trade. Off by default because it changes
    # optimizer semantics, not just cost.
    sparse_embedding_lazy: bool = False

    # ---- serving (flexflow_tpu.serve) ----
    # block-paged KV-cache geometry: the pool holds kv_num_pages pages
    # of kv_page_size tokens each, per layer; page 0 is reserved as the
    # write sink for padding lanes (serve/kv_cache.py). Sized so
    # (kv_num_pages - 1) * kv_page_size bounds the total resident
    # tokens across all concurrent sequences.
    kv_page_size: int = 16
    kv_num_pages: int = 257
    # KV-page storage format (serve/kv_cache.py): "float32" (exact),
    # "bfloat16" (rounds on write; exact for bf16-activation engines),
    # or "int8" (per-page scale arrays, quantize-on-write /
    # dequantize-at-read in the ragged kernel). Quantized pages cost
    # ~1/4 the bytes, so an equal byte budget holds ~2-4x the pages —
    # the concurrent-sequences-per-chip lever. The serving exactness
    # gate relaxes for lossy formats to bounded attention-output error
    # + greedy token parity (tests/test_kv_quant.py). --kv-dtype.
    kv_dtype: str = "float32"
    # size the page pool by BYTE budget instead of page count: when
    # > 0, kv_num_pages derives as 1 + budget // page_bytes(kv_dtype) —
    # computed from the configured dtype's itemsize (+ scale rows), so
    # flipping kv_dtype at a fixed budget changes the PAGE COUNT, and
    # every page-fraction knob (admission watermark, degradation-ladder
    # rungs) automatically sees the larger effective pool. 0 = use
    # kv_num_pages directly. --kv-pool-mb.
    kv_pool_mb: float = 0.0
    # hierarchical prefix-cache tier (serve/host_tier.py): byte budget
    # of the host-RAM page store below the HBM pool. When > 0 (and
    # serve_host_tier is on), LRU pages evicted under pressure spill
    # their bytes to host memory instead of being discarded, and a
    # later prefix match re-imports them when the priced DMA time
    # (TPUMachineModel.host_transfer) beats recompute. A ReplicaPool
    # shares ONE store across replicas. 0 = tier unarmed.
    # --host-tier-mb / --no-host-tier.
    host_tier_mb: float = 0.0
    serve_host_tier: bool = True
    # ragged-attention kv-block shape (kernels/paged_ragged_v2.py): KV
    # tokens each flattened (lane, kv-block) work item covers (rounded
    # to whole pages). 0 = the autotune-by-shape table
    # (choose_block_kv). --serve-attn-block-kv.
    serve_attn_block_kv: int = 0
    # AOT program cache directory (core/programs.py): serving engines
    # snapshot their compiled executables here keyed by a program
    # fingerprint (arch + lane widths + kv geometry + adapter + tp +
    # jax/backend version), and a cold engine — an autoscaler scale-up
    # with no parked replica, a fresh process — deserializes them
    # before the first request instead of paying the compile storm.
    # None = compile per process. --program-cache-dir.
    program_cache_dir: Optional[str] = None
    # continuous-batching scheduler caps (serve/scheduler.py): at most
    # serve_max_seqs sequences hold decode slots at once (this is also
    # the decode-lane reserve of the engine's single mixed step), and
    # one scheduler step computes at most serve_prefill_budget prompt
    # tokens of prefill work (FCFS; long prompts chunk across steps).
    serve_max_seqs: int = 8
    serve_prefill_budget: int = 512
    # chunked prefill (serve/engine.py): pack prompt chunks from any
    # number of requests together with every running decode token into
    # ONE fixed-shape program of serve_prefill_budget + serve_max_seqs
    # lanes — zero per-bucket recompiles, decode never stalls behind a
    # long prompt. --no-chunked-prefill falls back to the per-bucket
    # prefill + full-width decode pair.
    serve_chunked_prefill: bool = True
    # prefix caching (serve/kv_cache.py): completed KV pages are
    # content-hashed and shared copy-free across sequences via per-page
    # refcounts, so a prompt whose prefix is already resident skips
    # those tokens at prefill. Requires chunked prefill (the legacy
    # prefill program re-scatters every position). --no-prefix-cache.
    serve_prefix_cache: bool = True
    # admission watermark (fraction of the page pool that must stay
    # reclaimable after admitting a request's first chunk): with
    # on-demand page allocation the scheduler admits against ACTUAL
    # residency, and this headroom keeps admissions from thrashing the
    # preemption path the moment running sequences grow.
    serve_admit_watermark: float = 0.02
    # speculative decoding (serve/speculative.py): a host-side drafter
    # (prompt-lookup n-gram by default) proposes up to serve_spec_tokens
    # continuation tokens per decoding sequence per step; the mixed
    # program verifies them in spare lanes and the host keeps the
    # longest matching prefix — greedy outputs stay token-identical to
    # sequential decode. Draft length adapts per request from a
    # windowed acceptance rate (0 = auto-disabled on adversarial
    # text). Draft lanes compete with prefill chunks for
    # serve_prefill_budget; decode lanes never starve.
    # --spec-tokens N / --no-spec-decode.
    serve_spec_decode: bool = True
    serve_spec_tokens: int = 4
    # ---- robustness (utils/faults.py, docs/robustness.md) ----
    # deterministic fault injection: a spec string like
    # "serve.mixed:transient@2,5;serve.page_pressure:exhaust:0.5@3-9"
    # arms seeded failures at marked sites (engine dispatch, scheduler
    # page pressure, checkpoint commit) so chaos tests replay exactly.
    # None = no injection (also settable via FLEXFLOW_TPU_FAULTS).
    fault_spec: Optional[str] = None
    # default per-request wall-clock deadline in seconds for
    # ServeEngine.generate (0 = none): a request that has not finished
    # when its deadline passes is aborted at the next chunk boundary
    # with outcome "deadline_expired", its pages reclaimed.
    serve_request_deadline: float = 0.0
    # bounded retry-with-backoff around the engine's jitted dispatch
    # for TransientError (injected or tunnel hiccup): up to
    # serve_max_retries re-dispatches, sleeping
    # serve_retry_backoff_s * 2^attempt between them.
    serve_max_retries: int = 3
    serve_retry_backoff_s: float = 0.02
    # graceful-degradation ladder under page pressure
    # (serve/scheduler.py): rung 1 sheds speculation, rung 2 stops
    # prefix-matching + shrinks the parked LRU, rung 3 tightens the
    # admission watermark (floored at 8% of the pool), rung 4 rejects
    # (structured RejectedRequest)
    # what can never fit. --no-degrade-ladder freezes rung 0 behavior.
    serve_degrade_ladder: bool = True
    # opt-in online-serving rung-4 policy: reject the waiting head
    # after this many consecutive stalled admission attempts at rung
    # >= 3 (0 = never reject for stalling; offline batches wait).
    serve_reject_stalls: int = 0
    # tensor-parallel sharded serving (docs/serving.md "Sharded
    # serving"): shard the ONE mixed program over a 1-D "tensor" mesh —
    # head-parallel attention over a head-sharded KV page pool,
    # column/row-parallel projections with one all-reduce after the
    # attention output and FFN, vocab-sharded embedding/head with ONE
    # logits all-gather. "" (default) = single device; an integer
    # string = that tensor-parallel degree; "auto" = resolve the degree
    # through the placement search (search/serve_place.optimize_serve —
    # the SOAP-style simulator pricing applied to the serve program).
    # --serve-mesh.
    serve_mesh: str = ""
    # disaggregated prefill/decode serving (serve/disagg.py,
    # docs/serving.md "Disaggregated serving"): dedicated prefill
    # engines stream finished KV pages to dedicated decode engines
    # over a host-side page handoff, so decode steps stop paying for
    # the prefill budget's lanes (the TPOT tax of the ONE mixed
    # program). --serve-disagg enables it; serve_disagg_ratio is
    # "P:D" engine counts ("" = 1:1, "auto" = the placement search's
    # ratio table via optimize_serve(..., disaggregated=True) — the
    # SOAP don't-hand-tune-it discipline on a new axis);
    # serve_disagg_decode_budget is the decode role's prefill-lane
    # stub (tokens; 0 = 2 pages' worth — just enough to recompute a
    # handoff's partial tail page). --serve-disagg-ratio /
    # --serve-disagg-decode-budget.
    serve_disagg: bool = False
    serve_disagg_ratio: str = ""
    serve_disagg_decode_budget: int = 0
    # multi-replica serving tier (serve/router.py, docs/serving.md
    # "Multi-replica routing"): N engine replicas behind a request
    # router. serve_replicas sizes the starting pool
    # (--serve-replicas): an integer, or "auto" to resolve the
    # (tensor degree, replica count) shape through the 2-D serve-mesh
    # search (search/serve_place.optimize_serve_mesh, docs/search.md
    # "2-D serve mesh") — with --serve-mesh N the degree is pinned and
    # only the replica count is searched; with --serve-mesh auto the
    # ONE walk prices both. router_policy picks how requests land —
    # "affinity" routes to the replica whose chain-hash prefix
    # registry holds the LONGEST matching prefix of the prompt (a
    # host-side dict probe per page-aligned block; tenant-sticky
    # fallback hash when nothing matches, load-aware spill off
    # rung-3/occupancy pressure), "round_robin" is the A/B baseline
    # (--router-policy). slo_ttft_ms / slo_tpot_ms define
    # goodput-under-SLO — a request counts only when its TTFT and
    # per-token decode latency both meet target (0 = that bound is
    # waived) (--slo-ttft-ms / --slo-tpot-ms). serve_autoscale arms
    # the telemetry-driven replica autoscaler (TTFT/TPOT p99 +
    # pool-occupancy gauges vs the SLOs, priced against the placement
    # search's per-degree decode table; --autoscale), scaling between
    # 1 and serve_autoscale_max replicas (0 = 2x serve_replicas).
    serve_replicas: Union[int, str] = 1
    router_policy: str = "affinity"
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    serve_autoscale: bool = False
    serve_autoscale_max: int = 0
    # wall-clock serving fabric (docs/serving.md "Wall-clock mode"):
    # serve_wall_clock switches ReplicaPool.run to real time — each
    # replica steps on its own worker thread, arrivals pace on the
    # wall clock, and goodput-under-SLO is a measured wall number
    # (tokens stay identical to the virtual-clock run at one seed;
    # the autoscaler stays virtual-only). --wall-clock.
    # serve_transport moves disagg PageShipments across a
    # length-prefixed socket ("tcp"; "" = in-process handoff) with
    # the receiver enforcing the SAME serve_admit_watermark
    # backpressure; host/port pick the loopback receiver's bind
    # (port 0 = ephemeral). --transport / --transport-port.
    serve_wall_clock: bool = False
    serve_transport: str = ""
    serve_transport_host: str = "127.0.0.1"
    serve_transport_port: int = 0
    # multi-tenant LoRA adapter serving (serve/adapters.py,
    # docs/serving.md "Multi-tenant adapters"): adapter_rank > 0 arms
    # the HBM-resident adapter pool — fixed rank-padded (A, B) slab
    # pairs, one slot per resident tenant, gathered per lane inside
    # the ONE mixed program so tenant-heterogeneous batches decode in
    # one fixed-shape step (zero recompiles; needs chunked prefill).
    # adapter_pool_mb sizes the slot count by per-device byte budget
    # (the kv_pool_mb idiom; 0 = 1 + serve_max_seqs slots).
    # tenant_adapters is the synthetic tenant count traffic mixes and
    # the lora bench register (tenants 1..N, serve/traffic.py).
    # --adapter-rank / --adapter-pool-mb / --tenant-adapters.
    adapter_rank: int = 0
    adapter_pool_mb: float = 0.0
    tenant_adapters: int = 4

    # synthetic input when no dataset is provided (reference: config.h:131)
    synthetic_input: bool = False

    # mesh description: axis names/sizes. None = single device.
    mesh_shape: Optional[Sequence[int]] = None
    mesh_axes: Optional[Sequence[str]] = None

    iter_config: FFIterationConfig = dataclasses.field(
        default_factory=FFIterationConfig
    )

    # argv to parse at construction; None = don't touch the process argv
    # (a library must not hijack the host application's flags). Use
    # FFConfig.from_args() in driver scripts for reference CLI parity.
    argv: Optional[Sequence[str]] = None

    def __post_init__(self):
        if self.argv is not None:
            self.parse_args(self.argv)
        self.validate()

    def validate(self) -> None:
        """Reject silently-ignorable values (conv_layout falls back to
        NCHW on any non-"NHWC" string, which would be an undetectable
        perf misconfiguration). Called from __post_init__ and compile."""
        # normalize the precision policy to jnp dtypes (CLI hands us
        # strings like "bfloat16"); reject non-float dtypes loudly — an
        # int compute_dtype would silently break every cast site
        from .core.precision import resolve_dtype
        self.compute_dtype = resolve_dtype(self.compute_dtype,
                                           "compute_dtype")
        self.param_dtype = resolve_dtype(self.param_dtype, "param_dtype")
        if self.conv_layout not in ("NCHW", "NHWC"):
            raise ValueError(
                f"conv_layout must be 'NCHW' or 'NHWC', got "
                f"{self.conv_layout!r}")
        if self.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"pipeline_schedule must be 'gpipe' or '1f1b', got "
                f"{self.pipeline_schedule!r}")
        if self.moe_dispatch not in ("auto", "dense", "sorted"):
            raise ValueError(
                f"moe_dispatch must be 'auto', 'dense' or 'sorted', "
                f"got {self.moe_dispatch!r}")
        if self.sp_attention not in ("auto", "ring", "alltoall"):
            raise ValueError(
                f"sp_attention must be 'auto', 'ring' or 'alltoall', "
                f"got {self.sp_attention!r}")
        if self.pipeline_virtual_stages < 1:
            raise ValueError(
                f"pipeline_virtual_stages must be >= 1, got "
                f"{self.pipeline_virtual_stages}")
        if self.grad_bucket_mb is not None and self.grad_bucket_mb < 0:
            raise ValueError(
                f"grad_bucket_mb must be >= 0 (0 = monolithic sync, "
                f"unset = auto-tune), got {self.grad_bucket_mb}")
        if self.train_dispatch_depth < 0:
            raise ValueError(
                f"train_dispatch_depth must be >= 0 (0 = unbounded, "
                f"1 = synchronous), got {self.train_dispatch_depth}")
        if self.search_chains < 0:
            raise ValueError(
                f"search_chains must be >= 0 (0 = auto), got "
                f"{self.search_chains}")
        if self.kv_page_size < 1:
            raise ValueError(
                f"kv_page_size must be >= 1, got {self.kv_page_size}")
        if self.kv_num_pages < 2:
            raise ValueError(
                f"kv_num_pages must be >= 2 (page 0 is the serving "
                f"sink page), got {self.kv_num_pages}")
        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, "
                f"got {self.kv_dtype!r}")
        if self.kv_pool_mb < 0:
            raise ValueError(
                f"kv_pool_mb must be >= 0 (0 = size by kv_num_pages), "
                f"got {self.kv_pool_mb}")
        if self.host_tier_mb < 0:
            raise ValueError(
                f"host_tier_mb must be >= 0 (0 = host tier unarmed), "
                f"got {self.host_tier_mb}")
        if self.serve_attn_block_kv < 0:
            raise ValueError(
                f"serve_attn_block_kv must be >= 0 (0 = autotune), "
                f"got {self.serve_attn_block_kv}")
        if self.serve_max_seqs < 1:
            raise ValueError(
                f"serve_max_seqs must be >= 1, got {self.serve_max_seqs}")
        if self.serve_prefill_budget < 1:
            raise ValueError(
                f"serve_prefill_budget must be >= 1, got "
                f"{self.serve_prefill_budget}")
        if self.adapter_rank < 0:
            raise ValueError(
                f"adapter_rank must be >= 0 (0 = adapters unarmed), "
                f"got {self.adapter_rank}")
        if self.adapter_pool_mb < 0:
            raise ValueError(
                f"adapter_pool_mb must be >= 0 (0 = size by "
                f"serve_max_seqs), got {self.adapter_pool_mb}")
        if self.tenant_adapters < 0:
            raise ValueError(
                f"tenant_adapters must be >= 0, got "
                f"{self.tenant_adapters}")
        if self.adapter_rank > 0 and not self.serve_chunked_prefill:
            raise ValueError(
                "adapter_rank > 0 needs chunked prefill (the per-lane "
                "adapter gather lives in the ONE mixed program); drop "
                "--no-chunked-prefill")
        if not 0.0 <= self.serve_admit_watermark < 1.0:
            raise ValueError(
                f"serve_admit_watermark must be in [0, 1), got "
                f"{self.serve_admit_watermark}")
        if self.serve_spec_tokens < 0:
            raise ValueError(
                f"serve_spec_tokens must be >= 0 (0 disables "
                f"speculative decoding), got {self.serve_spec_tokens}")
        if self.serve_request_deadline < 0:
            raise ValueError(
                f"serve_request_deadline must be >= 0 (0 = none), got "
                f"{self.serve_request_deadline}")
        if self.serve_max_retries < 0:
            raise ValueError(
                f"serve_max_retries must be >= 0, got "
                f"{self.serve_max_retries}")
        if self.serve_retry_backoff_s < 0:
            raise ValueError(
                f"serve_retry_backoff_s must be >= 0, got "
                f"{self.serve_retry_backoff_s}")
        if self.serve_reject_stalls < 0:
            raise ValueError(
                f"serve_reject_stalls must be >= 0 (0 = never), got "
                f"{self.serve_reject_stalls}")
        sr = str(self.serve_disagg_ratio or "").strip()
        if sr and sr != "auto":
            parts = sr.split(":")
            ok = len(parts) == 2
            if ok:
                try:
                    ok = int(parts[0]) >= 1 and int(parts[1]) >= 1
                except ValueError:
                    ok = False
            if not ok:
                raise ValueError(
                    f"serve_disagg_ratio must be '', 'auto', or "
                    f"'P:D' with positive engine counts, got "
                    f"{self.serve_disagg_ratio!r}")
        if self.serve_disagg_decode_budget < 0:
            raise ValueError(
                f"serve_disagg_decode_budget must be >= 0 (0 = two "
                f"pages' worth), got {self.serve_disagg_decode_budget}")
        if isinstance(self.serve_replicas, str):
            if self.serve_replicas.strip() != "auto":
                raise ValueError(
                    f"serve_replicas must be an integer >= 1 or "
                    f"'auto', got {self.serve_replicas!r}")
        elif self.serve_replicas < 1:
            raise ValueError(
                f"serve_replicas must be >= 1, got "
                f"{self.serve_replicas}")
        if self.router_policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"router_policy must be 'affinity' or 'round_robin', "
                f"got {self.router_policy!r}")
        if self.slo_ttft_ms < 0 or self.slo_tpot_ms < 0:
            raise ValueError(
                f"slo_ttft_ms/slo_tpot_ms must be >= 0 (0 = no "
                f"bound), got {self.slo_ttft_ms}/{self.slo_tpot_ms}")
        if self.serve_autoscale_max < 0:
            raise ValueError(
                f"serve_autoscale_max must be >= 0 (0 = 2x "
                f"serve_replicas), got {self.serve_autoscale_max}")
        if str(self.serve_transport or "").strip() not in ("", "tcp"):
            raise ValueError(
                f"serve_transport must be '' (in-process) or 'tcp', "
                f"got {self.serve_transport!r}")
        if not 0 <= int(self.serve_transport_port) <= 65535:
            raise ValueError(
                f"serve_transport_port must be 0..65535 (0 = "
                f"ephemeral), got {self.serve_transport_port}")
        if self.serve_wall_clock and self.serve_autoscale:
            raise ValueError(
                "--wall-clock and --autoscale are mutually exclusive: "
                "the autoscaler replays on the virtual clock only")
        sm = str(self.serve_mesh or "").strip()
        if sm and sm != "auto":
            try:
                ok = int(sm) >= 1
            except ValueError:
                ok = False
            if not ok:
                raise ValueError(
                    f"serve_mesh must be '', 'auto', or a positive "
                    f"tensor-parallel degree, got {self.serve_mesh!r}")
        if self.telemetry_buffer_events < 1:
            raise ValueError(
                f"telemetry_buffer_events must be >= 1, got "
                f"{self.telemetry_buffer_events}")
        if self.telemetry_drift_threshold < 0:
            raise ValueError(
                f"telemetry_drift_threshold must be >= 0, got "
                f"{self.telemetry_drift_threshold}")
        if self.metrics_port is not None and not (
                0 <= int(self.metrics_port) <= 65535):
            raise ValueError(
                f"metrics_port must be None (off) or 0..65535 "
                f"(0 = ephemeral), got {self.metrics_port}")
        if self.postmortem_events < 1:
            raise ValueError(
                f"postmortem_events must be >= 1, got "
                f"{self.postmortem_events}")
        if not (0.0 < self.slo_error_budget <= 1.0):
            raise ValueError(
                f"slo_error_budget must be in (0, 1] (the tolerated "
                f"violation fraction), got {self.slo_error_budget}")
        if self.fault_spec:
            # parse eagerly so a typo'd spec fails at config time, not
            # silently mid-chaos-run
            from .utils.faults import FaultSpec
            FaultSpec(self.fault_spec)
        if self.pipeline_virtual_stages > 1 \
                and self.pipeline_schedule != "1f1b":
            raise ValueError(
                "pipeline_virtual_stages > 1 requires "
                "pipeline_schedule='1f1b' (interleaving lives in the "
                "explicit-gradient schedule)")

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "FFConfig":
        """Reference-style construction: parse CLI flags
        (FFConfig::parse_args, model.cc:2258-2379)."""
        return cls(argv=list(sys.argv[1:]) if argv is None else list(argv))

    # -- CLI parity (reference: FFConfig::parse_args model.cc:2258-2379) --
    _FLAG_MAP = {
        "-b": ("batch_size", int),
        "--batch-size": ("batch_size", int),
        "-e": ("epochs", int),
        "--epochs": ("epochs", int),
        "--iterations": ("iterations", int),
        "-lr": ("learning_rate", float),
        "--learning-rate": ("learning_rate", float),
        "-wd": ("weight_decay", float),
        "--weight-decay": ("weight_decay", float),
        "--search-budget": ("search_budget", int),
        "--budget": ("search_budget", int),
        "--search-alpha": ("search_alpha", float),
        "--alpha": ("search_alpha", float),
        "--search-chains": ("search_chains", int),
        "--cost-cache": ("cost_cache_file", str),
        "--import": ("import_strategy_file", str),
        "--import-strategy": ("import_strategy_file", str),
        "--export": ("export_strategy_file", str),
        "--export-strategy": ("export_strategy_file", str),
        "--machine-model-file": ("machine_model_file", str),
        "--taskgraph": ("taskgraph_file", str),
        "--seed": ("seed", int),
        "--grad-bucket-mb": ("grad_bucket_mb", float),
        "--train-dispatch-depth": ("train_dispatch_depth", int),
        "--compute-dtype": ("compute_dtype", str),
        "--param-dtype": ("param_dtype", str),
        "--conv-layout": ("conv_layout", str),
        "--measure-ops": ("measure_top_ops", int),
        "--moe-dispatch": ("moe_dispatch", str),
        "--sp-attention": ("sp_attention", str),
        "--pipeline-stages": ("pipeline_stages", int),
        "--pipeline-microbatches": ("pipeline_microbatches", int),
        "--pipeline-schedule": ("pipeline_schedule", str),
        "--pipeline-virtual-stages": ("pipeline_virtual_stages", int),
        "--kv-page-size": ("kv_page_size", int),
        "--kv-num-pages": ("kv_num_pages", int),
        "--kv-dtype": ("kv_dtype", str),
        "--kv-pool-mb": ("kv_pool_mb", float),
        "--host-tier-mb": ("host_tier_mb", float),
        "--program-cache-dir": ("program_cache_dir", str),
        "--serve-attn-block-kv": ("serve_attn_block_kv", int),
        "--serve-max-seqs": ("serve_max_seqs", int),
        "--serve-prefill-budget": ("serve_prefill_budget", int),
        "--adapter-rank": ("adapter_rank", int),
        "--adapter-pool-mb": ("adapter_pool_mb", float),
        "--tenant-adapters": ("tenant_adapters", int),
        "--serve-admit-watermark": ("serve_admit_watermark", float),
        "--spec-tokens": ("serve_spec_tokens", int),
        "--fault-spec": ("fault_spec", str),
        "--request-deadline": ("serve_request_deadline", float),
        "--serve-max-retries": ("serve_max_retries", int),
        "--serve-retry-backoff": ("serve_retry_backoff_s", float),
        "--serve-reject-stalls": ("serve_reject_stalls", int),
        "--serve-mesh": ("serve_mesh", str),
        "--serve-disagg-ratio": ("serve_disagg_ratio", str),
        "--serve-disagg-decode-budget": ("serve_disagg_decode_budget",
                                         int),
        "--serve-replicas": ("serve_replicas", _int_or_auto),
        "--router-policy": ("router_policy", str),
        "--slo-ttft-ms": ("slo_ttft_ms", float),
        "--slo-tpot-ms": ("slo_tpot_ms", float),
        "--autoscale-max": ("serve_autoscale_max", int),
        "--transport": ("serve_transport", str),
        "--transport-host": ("serve_transport_host", str),
        "--transport-port": ("serve_transport_port", int),
        "--trace-out": ("trace_out", str),
        "--trace-dir": ("trace_dir", str),
        "--telemetry-buffer": ("telemetry_buffer_events", int),
        "--drift-threshold": ("telemetry_drift_threshold", float),
        "--metrics-port": ("metrics_port", int),
        "--metrics-host": ("metrics_host", str),
        "--schedule-trace": ("schedule_trace_file", str),
        "--postmortem-dir": ("postmortem_dir", str),
        "--postmortem-events": ("postmortem_events", int),
        "--slo-error-budget": ("slo_error_budget", float),
    }
    _BOOL_FLAGS = {
        "--profiling": "profiling",
        "--fusion": "perform_fusion",
        "--remat": "remat",
        "--overlap": "search_overlap_backward_sync",
        "--enable-parameter-parallel": "enable_parameter_parallel",
        "--enable-attribute-parallel": "enable_attribute_parallel",
        "--enable-sample-parallel": "enable_sample_parallel",
        "--enable-sequence-parallel": "enable_sequence_parallel",
        "--enable-expert-parallel": "enable_expert_parallel",
        "--enable-pipeline-parallel": "enable_pipeline_parallel",
        "--enable-propagation": "enable_propagation",
        "--search-mesh-shapes": "search_mesh_shapes",
        "--enable-device-placement": "enable_device_placement",
        "--zero": "zero_optimizer_sharding",
        "--synthetic-input": "synthetic_input",
        "--sparse-embedding-lazy": "sparse_embedding_lazy",
        "--telemetry": "telemetry",
        "--serve-disagg": "serve_disagg",
        "--autoscale": "serve_autoscale",
        "--wall-clock": "serve_wall_clock",
    }
    _NEG_BOOL_FLAGS = {
        "--no-overlap-sync": "search_overlap_backward_sync",
        "--no-sparse-embedding": "sparse_embedding_updates",
        "--no-sibling-conv-fusion": "sibling_conv_fusion",
        "--no-delta-sim": "search_delta_sim",
        "--no-cost-cache": "search_cost_cache",
        "--no-chunked-prefill": "serve_chunked_prefill",
        "--no-prefix-cache": "serve_prefix_cache",
        "--no-host-tier": "serve_host_tier",
        "--no-spec-decode": "serve_spec_decode",
        "--no-degrade-ladder": "serve_degrade_ladder",
        "--no-search-trace": "search_trace",
        "--no-slo-monitor": "slo_monitor",
    }

    def parse_args(self, argv: Sequence[str]) -> None:
        i = 0
        argv = list(argv)
        while i < len(argv):
            a = argv[i]
            if a in self._FLAG_MAP and i + 1 < len(argv):
                field, typ = self._FLAG_MAP[a]
                setattr(self, field, typ(argv[i + 1]))
                i += 2
                continue
            if a in self._BOOL_FLAGS:
                setattr(self, self._BOOL_FLAGS[a], True)
                i += 1
                continue
            if a in self._NEG_BOOL_FLAGS:
                setattr(self, self._NEG_BOOL_FLAGS[a], False)
                i += 1
                continue
            if a == "--seq-length" and i + 1 < len(argv):
                self.iter_config.seq_length = int(argv[i + 1])
                i += 2
                continue
            i += 1

    # -- device/mesh introspection --
    @property
    def workers_per_node(self) -> int:
        return jax.local_device_count()

    @property
    def num_nodes(self) -> int:
        return jax.process_count()

    @property
    def num_devices(self) -> int:
        return jax.device_count()
