"""Per-op cost estimation under a candidate strategy.

The analog of the reference's `Op::measure_operator_cost` (real CUDA
kernels timed on GPU0, e.g. linear.cu:1000-1073) — but on TPU a candidate
strategy implies a recompile, so costs come from the roofline + collective
formulas in machine_model.py instead of per-candidate measurement
(SURVEY.md section 7 hard part (d)); measure.py calibrates the formulas'
efficiency factors against real jitted ops once per machine.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from ..op import Op
from ..parallel.pconfig import OpStrategy
from .machine_model import TPUMachineModel

# bump when any cost formula changes: part of the persistent cost-cache
# fingerprint (search/cost_cache.py), so stale entries computed by an
# older pricing model can never resurrect into a newer search.
# v2: dtype-aware pricing — flops at the compute dtype's MXU rate,
# bytes from actual itemsize (FFConfig.compute_dtype/param_dtype).
# v3: overlap-exact sync pricing — OpCost carries sync_bytes (the
# per-device DP payload) so the simulator can price bucket-granular
# grad syncs (FFConfig.grad_bucket_mb) with real per-bucket
# latency+bandwidth instead of one latency term per op.
# v4: serve-program pricing (ServeArch / serve_step_tasks) — the
# SOAP-style simulation applied to the ONE mixed prefill+decode
# serving step, per tensor-parallel degree and axis assignment
# (search/serve_place.optimize_serve resolves --serve-mesh auto).
# v5: disaggregated serving — the page-handoff transfer link priced on
# the machine model's host link (kv_handoff_bytes at the KV storage
# itemsize + scale rows; serve_step_tasks transfer_tokens) and the
# prefill:decode ratio search over per-role tensor degrees
# (serve_place.optimize_serve_disagg).
# v6: multi-tenant LoRA serving — ServeArch carries adapter_rank /
# adapter_slots, serve_step_tasks prices the per-lane slab gather and
# the low-rank delta flops on every adapted projection, and
# serve_device_bytes adds the adapter-pool HBM term so --serve-mesh
# auto trades tensor degree against adapter residency.
COST_MODEL_VERSION = 6

BWD_FLOP_FACTOR = 2.0  # dX and dW GEMMs ≈ 2x fwd (reference bwd = 2 GEMMs)
# per-op-type overrides: attention bwd recomputes probabilities from the
# saved logsumexp (flash custom-VJP) + 4 grad einsums ≈ 4x fwd
BWD_FACTOR_BY_TYPE = {"multihead_attention": 4.0}
MATMUL_OPS = {"linear", "conv2d", "batch_matmul", "multihead_attention",
              "lstm", "moe_ffn", "pipeline_blocks"}


@dataclasses.dataclass
class PipelineCost:
    """Per-stage costs for event-loop expansion of a pipelined op
    (reference simulator.cc:330-629 expands every task; our Python
    simulator expands pipeline units into (microbatch, stage) tasks).

    Uniform stages (pipeline_blocks) use the scalar fields; graph-level
    staged strategies (heterogeneous stages, core/staged.py) fill the
    per-stage/per-cut lists instead."""
    stages: int
    microbatches: int
    fwd_stage: float    # compute seconds of ONE (microbatch, stage) tick
    bwd_stage: float
    hop: float          # ppermute seconds per inter-stage activation hop
    fwd_stages: Optional[list] = None   # per-stage overrides
    bwd_stages: Optional[list] = None
    hops: Optional[list] = None         # per-cut overrides (len S-1)

    def fwd_at(self, k: int) -> float:
        return self.fwd_stages[k] if self.fwd_stages else self.fwd_stage

    def bwd_at(self, k: int) -> float:
        return self.bwd_stages[k] if self.bwd_stages else self.bwd_stage

    def hop_at(self, k: int) -> float:
        """Hop cost of the cut feeding stage k (k >= 1)."""
        return self.hops[k - 1] if self.hops else self.hop


@dataclasses.dataclass
class OpCost:
    fwd: float          # compute seconds, sharded
    bwd: float
    fwd_comm: float     # collective seconds attributable to fwd
    bwd_comm: float
    sync: float         # gradient sync (DP all-reduce) seconds
    mem: float          # bytes resident per device (weights+opt+acts)
    # optimizer-update sweep seconds (HBM-bound; the reference's update
    # tasks carry run_time=0, simulator.cc:420 — priced here beyond
    # parity). Kept separate from bwd so measured grounding replaces
    # kernel time without losing the update term; task builders add
    # bwd + update.
    update: float = 0.0
    # per-device bytes this op contributes to the DP gradient all-reduce
    # (the payload behind `sync`); 0 when no data-axis sync exists. The
    # simulator sums these over a bucket's members to price ONE combined
    # all-reduce per bucket (grad_bucket_mb) — real per-bucket
    # latency+bandwidth instead of a latency term per op.
    sync_bytes: float = 0.0
    # set for pipeline_blocks ops with layer->pipe mapped; fwd/bwd then
    # hold the closed-form GPipe makespan (used by the native engine's
    # one-task-per-op lowering) while the Python simulator replaces them
    # with the expanded per-stage schedule.
    pipeline: Optional[PipelineCost] = None

    def merge(self, other: "OpCost") -> "OpCost":
        """Fold another op's cost into one fused task (reference FusedOp:
        one launch for the group). Everything is additive — fwd/bwd_comm
        model each op's INTRINSIC collectives (e.g. a TP all-reduce),
        which fusion does not remove; what fusion avoids is resharding
        between members, and same-strategy chains never had any."""
        return OpCost(fwd=self.fwd + other.fwd, bwd=self.bwd + other.bwd,
                      fwd_comm=self.fwd_comm + other.fwd_comm,
                      bwd_comm=self.bwd_comm + other.bwd_comm,
                      sync=self.sync + other.sync, mem=self.mem + other.mem,
                      update=self.update + other.update,
                      sync_bytes=self.sync_bytes + other.sync_bytes,
                      pipeline=self.pipeline or other.pipeline)


def op_precision(op: Op) -> Tuple[str, float, float]:
    """(compute dtype name, compute itemsize, param itemsize) of the
    op's model — the precision policy the EXECUTOR will run
    (FFConfig.compute_dtype/param_dtype), so the search prices the step
    that actually executes. Weight specs are f32-declared throughout
    (builder bf16 is an ACTIVATION dtype), so scaling weight bytes by
    itemsize/4 is exact."""
    cfg = getattr(getattr(op, "model", None), "config", None)
    cd = jnp.dtype(getattr(cfg, "compute_dtype", jnp.float32)
                   if cfg is not None else jnp.float32)
    pd = jnp.dtype(getattr(cfg, "param_dtype", jnp.float32)
                   if cfg is not None else jnp.float32)
    return cd.name, float(cd.itemsize), float(pd.itemsize)


def _float_tensor_bytes(tensors, itemsize: float) -> float:
    """Bytes moved for a tensor list under a compute itemsize: float
    tensors stream at the compute dtype, integer tensors (embedding
    indices) keep their own width."""
    total = 0.0
    for t in tensors:
        if jnp.issubdtype(t.dtype, jnp.floating):
            total += t.num_elements * itemsize
        else:
            total += t.size_bytes()
    return total


def _axis_size(strategy: OpStrategy, mesh, logical_axis) -> int:
    ax = strategy.mesh_axis_for(logical_axis)
    if not isinstance(ax, str):
        return 1
    return mesh.shape.get(ax, 1)


def _axis_name(strategy: OpStrategy, logical_axis) -> Optional[str]:
    ax = strategy.mesh_axis_for(logical_axis)
    return ax if isinstance(ax, str) else None


def compute_shards(op: Op, strategy: OpStrategy, mesh) -> int:
    """Product of mesh-axis sizes over which this op's compute divides,
    honoring divisibility like sharding.spec_for_axes."""
    used = set()
    total = 1
    out_shape = op.outputs[0].shape if op.outputs else ()
    for i, ax in enumerate(op.output_axes()[0] if op.outputs else ()):
        name = _axis_name(strategy, ax)
        if name is None or name in used or name not in mesh.shape:
            continue
        size = mesh.shape[name]
        if i < len(out_shape) and out_shape[i] % size != 0:
            continue
        used.add(name)
        total *= size
    return max(1, total)


def op_cost(op: Op, strategy: OpStrategy, mesh,
            mm: TPUMachineModel, optimizer_state_mult: float = 3.0
            ) -> OpCost:
    shards = compute_shards(op, strategy, mesh)
    flops = op.flops()
    # --- precision policy (FFConfig.compute_dtype/param_dtype): float
    # activations stream (and collectives carry) compute-dtype bytes;
    # master weights + gradients stream param-dtype bytes (the cast
    # boundary upcasts cotangents before they reach the update); MXU
    # flops price at the compute dtype's per-dtype peak. This is the
    # dominant TPU perf lever (bf16 ≈ 2x rate, half the bytes) and the
    # whole point of making the search dtype-aware.
    cd_name, c_item, p_item = op_precision(op)
    cs = c_item / 4.0   # compute-dtype scale vs the f32-declared bytes
    ps = p_item / 4.0   # param-dtype scale
    act_bytes = _float_tensor_bytes(op.outputs, c_item)
    in_bytes = _float_tensor_bytes(op.inputs, c_item)
    w_bytes = op.weight_bytes()     # master (f32-declared) basis
    w_compute = w_bytes * cs        # the cast copies fwd/bwd stream
    is_mm = op.op_type in MATMUL_OPS
    # conv has its own MEASURED MXU fraction (measure.py
    # measure_conv_efficiency — the analog of the reference's per-shape
    # conv algorithm measurement, conv_2d.cu:173-260)
    kind = "conv" if op.op_type == "conv2d" else None

    dp = _axis_size(strategy, mesh, "sample")
    tp_axis = _axis_name(strategy, "channel_out")
    tp = _axis_size(strategy, mesh, "channel_out")
    head_tp = _axis_size(strategy, mesh, "head")
    seq_ax = _axis_name(strategy, "seq")
    sp = _axis_size(strategy, mesh, "seq")
    ep_ax = _axis_name(strategy, "expert")
    ep = _axis_size(strategy, mesh, "expert")
    pp_ax = _axis_name(strategy, "layer")
    pp = _axis_size(strategy, mesh, "layer")

    fwd_comm = 0.0
    bwd_comm = 0.0
    sync = 0.0

    # Embedding ops never stream the whole table: forward gathers only
    # the touched rows, and backward writes either the touched rows
    # (executor sparse-update path, when the indices are graph inputs)
    # or a dense table gradient (fallback). Price each accordingly —
    # w_bytes in the generic formula would overprice forward by the
    # vocab/batch ratio (10^3-10^5 for DLRM) and misrank strategies.
    # The same traffic numbers feed the device-placement branch below,
    # so placed and mesh-sharded candidates compete on equal pricing.
    sync_bytes = w_bytes * ps       # grads sync at the param dtype
    sync_data_sharded = False  # dense grads are replicated across dp
    fwd_bytes = bwd_bytes = act_bytes + in_bytes + w_compute
    if op.op_type in ("embedding", "distributed_embedding"):
        # forward gathers rows at the compute dtype; backward's row
        # gradients land at the param dtype (scatter into the master)
        n_idx = sum(t.num_elements for t in op.inputs)
        rows_bytes = c_item * op.out_dim * n_idx
        grad_rows_bytes = p_item * op.out_dim * n_idx
        cfg = op.model.config
        input_uids = {t.uid for t in op.model.input_tensors}
        # mirror the EXECUTOR's eligibility gate (executor.py
        # _sparse_table_ops) — including the optimizer's sparse_mode and
        # the lazy opt-in — so the search never prices a path the
        # executor won't take; unknown optimizer (search before
        # compile's assignment) prices dense, the conservative choice
        opt = getattr(op.model, "optimizer", None)
        mode = opt.sparse_mode() if opt is not None else None
        sparse_updates = (
            getattr(cfg, "sparse_embedding_updates", False)
            and (mode == "exact" or (
                mode == "lazy"
                and getattr(cfg, "sparse_embedding_lazy", False)))
            and all(t.uid in input_uids for t in op.inputs))
        grad_bytes = grad_rows_bytes if sparse_updates else w_bytes * ps
        fwd_bytes = act_bytes + in_bytes + rows_bytes
        bwd_bytes = act_bytes + in_bytes + grad_bytes
        sync_bytes = grad_bytes
        sync_data_sharded = sparse_updates  # each replica syncs its rows
        is_mm = False  # gather/scatter, never the MXU path
        emb_sparse_updates = sparse_updates
    else:
        emb_sparse_updates = False

    # --- device-explicit placement (reference ParallelConfig.device_ids,
    # config.h:47-73; DLRM per-table strategies dlrm_strategy.cc:1-50):
    # the op runs whole on its device set — no sample/model sharding —
    # and its output is gathered to the rest of the mesh (priced as one
    # ring all-gather); gradients flow back the same path. No DP weight
    # replica exists, so there is no gradient sync. Memory is averaged
    # over the mesh (exact when equal-size placed ops round-robin over
    # all devices, as the DLRM strategy does).
    if op.op_type == "distributed_embedding":
        # normalize to the UNPADDED (num_tables) basis: weight_specs
        # reflects num_slots once a placement was applied to the live
        # op, and pricing a new candidate from the padded bytes would
        # double-count (the placement A/B's simulate-after-compile
        # pattern hit exactly this)
        slots = max(1, getattr(op, "num_slots", 1))
        ntab = max(1, getattr(op, "num_tables", 1))
        w_bytes = w_bytes * ntab / slots
    devices = strategy.device_ids
    if devices:
        # a length-1 id is the whole-op pin shorthand the executor
        # expands to every table (ops/embedding.py apply_placement) —
        # price what will actually run
        ntab = getattr(op, "num_tables", None)
        if (op.op_type == "distributed_embedding" and ntab
                and len(devices) == 1):
            devices = tuple(devices) * ntab
        # distinct devices = real concurrency (a per-table id tuple may
        # assign several tables to one device; executed via the op's
        # slot layout)
        k = max(1, len(set(devices)))
        # slot-layout pad factor: the executable lowering pads every
        # device to the largest per-device group, so skewed assignments
        # inflate the kernel — price it so search prefers balance
        if (op.op_type == "distributed_embedding"
                and len(devices) == ntab):
            from collections import Counter
            kmax = max(Counter(devices).values())
            n_total = max(1, int(mesh.size))
            w_bytes *= n_total * kmax / len(devices)
        n = max(1, int(mesh.size))
        fwd = mm.compute_time(flops / k, fwd_bytes / k, is_mm, kind=kind,
                              dtype=cd_name)
        if op.op_type in ("embedding", "distributed_embedding"):
            bwd = mm.compute_time(flops / k, bwd_bytes / k, is_mm,
                                  kind=kind, dtype=cd_name)
        else:
            bwd = BWD_FACTOR_BY_TYPE.get(op.op_type,
                                         BWD_FLOP_FACTOR) * fwd
        if n > k:
            fwd_comm = mm.all_gather(act_bytes, n)
            bwd_comm = mm.all_gather(act_bytes, n)
        mem = (w_bytes * (ps + optimizer_state_mult) + act_bytes * 2) \
            * k / n
        # dense updates sweep the (NORMALIZED) table bytes — sync_bytes
        # was captured before the padded-slot normalization above and
        # would overprice a live placed op by slots/ntab
        upd_basis = sync_bytes if emb_sparse_updates else w_bytes * ps
        upd = (upd_basis * (2.0 + 2.0 * optimizer_state_mult) / k
               / (mm.spec.hbm_bandwidth * mm.efficiency["elementwise"])
               if w_bytes > 0 else 0.0)
        return OpCost(fwd=fwd, bwd=bwd, fwd_comm=fwd_comm,
                      bwd_comm=bwd_comm, sync=0.0, mem=mem, update=upd)

    fwd = mm.compute_time(flops / shards, fwd_bytes / shards, is_mm,
                          kind=kind, dtype=cd_name)
    if op.op_type in ("embedding", "distributed_embedding"):
        bwd = mm.compute_time(flops / shards, bwd_bytes / shards, is_mm,
                              kind=kind, dtype=cd_name)
    else:
        bwd = BWD_FACTOR_BY_TYPE.get(op.op_type, BWD_FLOP_FACTOR) * fwd

    # --- TP (Megatron pattern): fwd all-reduce of the (data-sharded)
    # output when the contraction dim is sharded; bwd all-reduce of the
    # input grad. (The reference hand-built this as replica tensors +
    # backward2 reduction, linear.cu:144-270.)
    eff_tp = max(tp, head_tp)
    if eff_tp > 1 and op.op_type in ("linear", "multihead_attention",
                                     "conv2d", "lstm"):
        fwd_comm += mm.all_reduce(act_bytes / dp, eff_tp, tp_axis)
        bwd_comm += mm.all_reduce(in_bytes / dp, eff_tp, tp_axis)

    # --- embedding vocab sharding: output psum over vocab axis
    vocab = _axis_size(strategy, mesh, "vocab")
    if vocab > 1 and op.op_type in ("embedding", "distributed_embedding"):
        fwd_comm += mm.all_reduce(act_bytes / dp, vocab,
                                  _axis_name(strategy, "vocab"))
        bwd_comm += mm.all_reduce(act_bytes / dp, vocab,
                                  _axis_name(strategy, "vocab"))

    # --- table sharding (DistributedEmbedding): vocab-complete tables
    # distributed over the axis — lookups run where the tables live,
    # outputs all-gather (the executable form of per-device placement)
    table = _axis_size(strategy, mesh, "table")
    if table > 1 and op.op_type == "distributed_embedding" \
            and op.num_tables % table != 0:
        # the executor's spec_for_axes silently drops a non-dividing
        # axis (weight stays replicated) — price it the same way
        table = 1
    if table > 1 and op.op_type == "distributed_embedding":
        fwd /= table
        bwd /= table
        fwd_comm += mm.all_gather(act_bytes / dp, table,
                                  _axis_name(strategy, "table"))
        bwd_comm += mm.all_gather(act_bytes / dp, table,
                                  _axis_name(strategy, "table"))

    # --- SP attention: priced per the lowering that actually executes
    # (parallel/ulysses.sp_mode_for — the op consults the same policy)
    if sp > 1 and op.op_type == "multihead_attention":
        from ..parallel.ulysses import sp_mode_for
        b, s_q = op.inputs[0].shape[0], op.inputs[0].shape[1]
        # key input carries the kv length in cross-attention
        s_kv = (op.inputs[1].shape[1] if len(op.inputs) > 1
                else s_q)
        mode = sp_mode_for(
            getattr(op.model.config, "sp_attention", "auto"),
            num_heads=getattr(op, "num_heads", 1), seq_size=sp,
            batch_local=max(1, b // max(1, dp)), seq_q=s_q, seq_kv=s_kv)
        if mode == "alltoall":
            # fwd: q,k,v head-scatter + out seq-scatter = 4 all-to-alls
            # of one activation shard; bwd mirrors them
            act = in_bytes / 3 / max(1, dp)
            fwd_comm += 4 * mm.all_to_all(act / sp, sp, seq_ax)
            bwd_comm += 4 * mm.all_to_all(act / sp, sp, seq_ax)
        else:
            # ring: (S-1) kv-shard hops each way
            kv_bytes = 2 * in_bytes / 3 / max(1, dp)  # k+v of the three
            fwd_comm += (sp - 1) * mm.ppermute(kv_bytes / sp, seq_ax)
            bwd_comm += 2 * (sp - 1) * mm.ppermute(kv_bytes / sp, seq_ax)

    # --- EP: dispatch + combine all-to-alls of the capacity buffers
    if ep > 1 and op.op_type == "moe_ffn":
        disp_bytes = (op.num_experts * op.capacity * op.in_dim
                      * c_item) / dp
        fwd_comm += 2 * mm.all_to_all(disp_bytes / ep, ep, ep_ax)
        bwd_comm += 2 * mm.all_to_all(disp_bytes / ep, ep, ep_ax)

    # --- PP: stages divide the layer stack, so per-device compute is
    # fwd/pp; the GPipe schedule stretches that by the bubble factor
    # (M + pp - 1)/M. fwd/bwd carry the closed-form makespan (native
    # engine's one-task-per-op view); `pipeline` carries the per-stage
    # tick costs so the Python simulator can run the real schedule.
    # optimizer-update sweep (see the `update` computation below) —
    # needed early here so pipelined ops fold it into their per-stage
    # ticks (the Python simulator prices expanded pipelines from
    # PipelineCost, never from OpCost.update)
    def update_sweep(divisor: float) -> float:
        if w_bytes <= 0:
            return 0.0
        upd_bytes = sync_bytes * (2.0 + 2.0 * optimizer_state_mult)
        per_dev = upd_bytes / max(1.0, divisor)
        if sync_data_sharded:
            per_dev /= max(1, dp)
        return per_dev / (mm.spec.hbm_bandwidth
                          * mm.efficiency["elementwise"])

    pipeline = None
    if pp > 1 and op.op_type == "pipeline_blocks":
        M = op.num_microbatches
        upd = update_sweep(eff_tp * ep * pp * vocab * table)
        fwd_stage = fwd / (pp * M)
        # each stage's weights update once per step; amortized over the
        # M bwd ticks so BOTH engines and the expanded schedule carry it
        bwd_stage = bwd / (pp * M) + upd / M
        mb_bytes = in_bytes / max(1, dp) / M
        hop = mm.ppermute(mb_bytes, pp_ax)
        pipeline = PipelineCost(stages=pp, microbatches=M,
                                fwd_stage=fwd_stage, bwd_stage=bwd_stage,
                                hop=hop)
        bubble = (M + pp - 1) / (M * pp)
        fwd *= bubble
        bwd = bwd * bubble + upd  # closed form (native engine view)
        fwd_comm += (M + pp - 1) * hop
        bwd_comm += (M + pp - 1) * hop

    # --- DP gradient sync: all-reduce of each weight's grad over the
    # data axis (the reference's NCCL all-reduce / PS update+prefetch,
    # optimizer_kernel.cu:113-180)
    payload = 0.0
    if dp > 1 and sync_bytes > 0:
        # weights sharded over model/expert/pipe/vocab/table axes reduce
        # per-device grad bytes proportionally; sparse-updated embedding
        # rows are additionally data-sharded (each replica contributes
        # only its batch shard's rows)
        payload = sync_bytes / max(1, eff_tp * ep * pp * vocab * table)
        if sync_data_sharded:
            payload /= dp
        sync = mm.all_reduce(payload, dp, _axis_name(strategy, "sample"))

    # --- memory: master weights at param_dtype + optimizer state
    # (f32 slots, counted on the declared-bytes basis) + compute-dtype
    # activations per device
    w_per_dev = w_bytes / max(1, eff_tp * ep * pp * vocab * table)
    act_per_dev = act_bytes / shards
    mem = w_per_dev * (ps + optimizer_state_mult) + act_per_dev * 2

    # --- optimizer update: the reference's update tasks carry
    # run_time=0 ("assume update takes no time", simulator.cc:420) —
    # but the elementwise sweep reads grads+weights+slots and writes
    # weights+slots, HBM-bound and significant for table-heavy models.
    # Priced beyond reference parity; sparse-updated embeddings sweep
    # only their touched rows (grad_bytes above). Serialized onto the
    # device after backward (folded into bwd so BOTH search engines
    # price it identically with no task-graph/ABI change).
    # pipelined ops already folded the sweep into their stage ticks /
    # closed-form bwd above — a nonzero field would double-count
    update = (0.0 if pipeline is not None
              else update_sweep(eff_tp * ep * pp * vocab * table))

    return OpCost(fwd=fwd, bwd=bwd, fwd_comm=fwd_comm, bwd_comm=bwd_comm,
                  sync=sync, mem=mem, update=update, sync_bytes=payload,
                  pipeline=pipeline)


def staged_pipeline_cost(model, mesh, mm: TPUMachineModel,
                         stage_of: Dict[str, int], microbatches: int,
                         schedule: str = "gpipe",
                         optimizer_state_mult: float = 3.0,
                         n_dev: Optional[int] = None):
    """Price a graph-level staged strategy (core/staged.py): the whole
    model runs as one pipeline whose per-stage tick costs are the sum of
    that stage's ops at microbatch granularity; hops carry the cut
    tensors. Returns (PipelineCost, per_stage_sync, total_mem).

    Mirrors what executes: no intra-stage sharding except the data axis
    over microbatch samples; per-stage weight grads all-reduce over data
    replicas; activation stash scales with the schedule's peak
    (M for GPipe, min(S - s, M) for 1F1B — the 1F1B memory story)."""
    from ..parallel.graph_pipeline import build_stage_plan
    plan = build_stage_plan(model, stage_of)
    S = plan.num_stages
    M = max(1, int(microbatches))
    ndata = mesh.shape.get("data", 1)
    local = OpStrategy({"sample": "data"})  # data split only
    # precision policy, applied like op_cost does: compute-dtype
    # activation bytes (stash + wire), param-dtype master weights,
    # f32-basis optimizer slots, param-dtype grad sync — a staged bf16
    # candidate must not be memory-penalized on f32 bytes while the
    # non-staged strategies it competes with are priced at bf16
    _, c_item, p_item = op_precision(model.ops[0]) if model.ops \
        else ("float32", 4.0, 4.0)
    ps = p_item / 4.0
    fwd_stages, bwd_stages, syncs, mems = [], [], [], []
    for s, ops in enumerate(plan.stages):
        f = b = sync_bytes = w_bytes = act_bytes = 0.0
        for op in ops:
            c = op_cost(op, local, mesh, mm,
                        optimizer_state_mult=optimizer_state_mult)
            f += c.fwd / M
            # the update sweep runs once per STEP, not per microbatch —
            # amortize it over the M bwd ticks like the Python executor
            # applies one optimizer step per dispatch
            b += (c.bwd + c.update) / M
            w = op.weight_bytes()
            sync_bytes += w * ps
            w_bytes += w
            act_bytes += _float_tensor_bytes(op.outputs,
                                             c_item) / ndata
        fwd_stages.append(f)
        bwd_stages.append(b)
        syncs.append(mm.all_reduce(sync_bytes, ndata, "data")
                     if ndata > 1 and sync_bytes > 0 else 0.0)
        peak = M if schedule != "1f1b" else min(S - s, M)
        mems.append(w_bytes * (ps + optimizer_state_mult)
                    + act_bytes / M * max(1, peak) * 2)
    hops = []
    # the inter-stage wire carries float activations at the compute
    # dtype (graph_pipeline._wire_layouts) — price the hops the same
    for cut in plan.cuts:
        cut_bytes = _float_tensor_bytes(cut, c_item) / M / ndata
        hops.append(mm.ppermute(cut_bytes, "pipe"))
    pc = PipelineCost(
        stages=S, microbatches=M,
        fwd_stage=sum(fwd_stages) / S, bwd_stage=sum(bwd_stages) / S,
        hop=(sum(hops) / len(hops)) if hops else 0.0,
        fwd_stages=fwd_stages, bwd_stages=bwd_stages, hops=hops)
    # per-device memory: one stage per device normally; under an
    # interleaved layout (n_dev < S, passed by the caller who knows the
    # compile lowering) device d owns the round-robin stage set
    # {d, d+n_dev, ...} and holds ALL their rows
    if n_dev is None:
        n_dev = S
    if mems and S > n_dev > 0 and S % n_dev == 0:
        mem_total = max(sum(mems[d::n_dev]) for d in range(n_dev))
    else:
        mem_total = max(mems) if mems else 0.0
    return pc, syncs, mem_total


# ---------------------------------------------------------------------------
# Serve-program pricing (tensor-parallel sharded serving, PR 9)
# ---------------------------------------------------------------------------

# the serve mesh's one axis name, shared with parallel/mesh.TENSOR
# (imported lazily there to keep this module jax-light)
SERVE_AXIS = "tensor"


@dataclasses.dataclass(frozen=True)
class ServeArch:
    """What the placement search needs to know about one ServeEngine:
    the LM's dimensions plus the serving workload's steady state. Built
    by ``ServeEngine.serve_arch()``; priced by :func:`serve_step_tasks`
    per tensor-parallel degree. ``context`` is the assumed resident
    KV history per decode lane (the attention/KV-streaming term);
    ``decode_lanes``/``prefill_lanes`` are the two steady-state
    workloads the ONE mixed program alternates between — a full decode
    step and a budget-sized prefill chunk."""

    num_layers: int
    hidden: int
    num_heads: int
    head_dim: int
    ff_dim: int
    vocab: int
    decode_lanes: int = 8
    prefill_lanes: int = 512
    context: int = 1024
    # steady-state output length per request — the decode-side work a
    # disaggregated ratio search balances against one prompt's prefill
    # chunks + page handoff (optimize_serve_disagg)
    decode_tokens: int = 64
    # the disaggregated decode role's prefill-lane stub (the cluster's
    # serve_disagg_decode_budget, default two pages): its fixed
    # program dispatches decode_lanes + THIS many lanes every step, so
    # the ratio search must price that width, not bare decode_lanes
    handoff_stub_lanes: int = 32
    # multi-tenant LoRA pool (serve/adapters.py): the fixed slab rank
    # and the pool's slot count (0 = adapters unarmed). Both are
    # signature() fields, so arming adapters — or resizing the pool —
    # is a guaranteed cost-cache miss.
    adapter_rank: int = 0
    adapter_slots: int = 0
    kv_dtype: str = "float32"
    kv_itemsize: float = 4.0
    kv_scales: bool = False      # quantized pools stream f32 scale rows
    act_itemsize: float = 4.0
    act_dtype: str = "float32"
    param_itemsize: float = 4.0  # serving weights as resident on device

    def signature(self) -> tuple:
        """Stable tuple of every field the pricing reads — the
        cost-cache entry key half (serve_place folds it in), so an
        arch OR kv/act dtype flip is a guaranteed cache miss."""
        return tuple(getattr(self, f.name)
                     for f in dataclasses.fields(self))

    def weight_bytes(self) -> float:
        """Total LM weight bytes at param_itemsize (qkv + wo + ffn per
        layer, tied-vocab embedding + head)."""
        e, hd = self.hidden, self.num_heads * self.head_dim
        per_layer = 3 * e * hd + hd * e + 2 * e * self.ff_dim
        return (self.num_layers * per_layer + 2 * self.vocab * e) \
            * self.param_itemsize


@dataclasses.dataclass
class ServeTask:
    """One node of the serve-step task graph (the serving analog of
    the training simulator's _Task): compute tasks run on the MXU/HBM
    roofline, collective tasks on the ICI ring formulas. deps name
    earlier tasks; simulator.simulate_serve_tasks runs the critical
    path."""
    name: str
    kind: str            # "compute" | "collective"
    seconds: float
    deps: tuple = ()


def kv_handoff_bytes(arch: ServeArch,
                     tokens: Optional[int] = None) -> float:
    """Host-link bytes of ONE prefill->decode page handoff: `tokens`
    (default: the arch's steady-state context) of K and V across every
    layer at the PAGE STORAGE dtype's itemsize, plus the f32 per-row
    scale arrays on quantized pools — exactly what
    ServeEngine.export_kv ships (serve/disagg.py). This is the term
    that makes a KV-dtype flip change the priced transfer cost: int8
    pages cost ~1/4 the f32 bytes on the link, the same 4x lever they
    are in HBM."""
    n = max(1, int(arch.context if tokens is None else tokens))
    hd = arch.num_heads * arch.head_dim
    b = 2.0 * n * hd * arch.num_layers * arch.kv_itemsize
    if arch.kv_scales:
        b += 2.0 * n * arch.num_heads * arch.num_layers * 4.0
    return b


def serve_step_tasks(arch: ServeArch, tensor_parallel: int,
                     mm: TPUMachineModel, *, lanes: int,
                     axis: str = SERVE_AXIS,
                     transfer_tokens: int = 0) -> list:
    """Task graph of ONE mixed serving step with ``lanes`` query lanes
    sharded ``tensor_parallel`` ways on the serve mesh (docs/serving.md
    "Sharded serving"), priced exactly like the engine executes it:

      per layer — head-column-parallel qkv, paged attention over each
      lane's ``context`` KV at ``kv_itemsize`` (plus f32 scale rows on
      quantized pools), head-row-parallel wo with its all-reduce,
      column→row-parallel FFN with its all-reduce; then the
      vocab-sharded head with the program's ONE logits all-gather
      (the embedding psum rides the first layer's entry).

    Weights stream at ``param_itemsize`` (serving is small-batch: the
    HBM weight traffic is the t× lever), activations/collectives at
    ``act_itemsize``. Returns [ServeTask] in dependency order.

    ``transfer_tokens`` > 0 adds the disaggregated page-handoff link:
    a ``kv_handoff`` task of kind "transfer" pricing that many tokens'
    KV pages over the host link (:func:`kv_handoff_bytes` at the KV
    storage itemsize + scale rows). It carries NO deps — the host-side
    DMA runs beside the device step, so it lengthens the makespan only
    when the link, not the compute, is the bottleneck (exactly how a
    decode engine imports one request's pages while decoding the
    others)."""
    t = max(1, int(tensor_parallel))
    T = int(lanes)
    e, h, d, f = arch.hidden, arch.num_heads, arch.head_dim, arch.ff_dim
    hd = h * d
    act = arch.act_itemsize
    p = arch.param_itemsize
    ctx = max(1, int(arch.context))
    dt = arch.act_dtype
    tasks: list = []

    def compute(name, flops, bytes_moved, deps):
        tasks.append(ServeTask(
            name, "compute",
            mm.compute_time(flops, bytes_moved, True, dtype=dt),
            deps))

    def all_reduce(name, nbytes, deps):
        if t > 1:
            tasks.append(ServeTask(
                name, "collective", mm.all_reduce(nbytes, t, axis),
                deps))

    # multi-tenant LoRA deltas (serve/adapters.py): every lane gathers
    # its tenant's (A, B) slabs by slot index and adds
    # (x @ A) @ B * scale on each adapted projection. The gather's HBM
    # traffic streams at most min(lanes, slots) distinct slots' slabs
    # (the A factors and replicated-output B factors replicate; the
    # head/ff-sharded factors divide by t); the delta flops ride the
    # projection tasks they extend.
    r = max(0, int(arch.adapter_rank))
    lora_qkv = lora_wo = lora_ffn = 0.0
    if r > 0:
        n_ad = min(T, max(1, int(arch.adapter_slots)))
        rep_slab = arch.num_layers * (3 * e * r + 3 * r * e) * act
        shd_slab = arch.num_layers * (3 * r * hd + hd * r
                                      + r * f + f * r) * act / t
        lora_qkv = 3 * (2 * T * e * r + 2 * T * r * hd / t)
        lora_wo = 2 * T * (hd / t) * r + 2 * T * r * e
        lora_ffn = (2 * T * e * r + 2 * T * r * f / t
                    + 2 * T * (f / t) * r + 2 * T * r * e)
    # vocab-row-sharded embedding: gather T rows locally, ONE exact
    # psum assembles them (engine._embed_tp)
    compute("embed", 0.0, T * e * act, ())
    all_reduce("embed_psum", T * e * act, ("embed",))
    prev = tasks[-1].name
    if r > 0:
        compute("adapter_gather", 0.0, n_ad * (rep_slab + shd_slab),
                (prev,))
        prev = "adapter_gather"
    for i in range(arch.num_layers):
        # head-column-parallel qkv (each device its H/t heads)
        compute(f"l{i}.qkv", 2 * 3 * T * e * hd / t + lora_qkv,
                (3 * e * hd * p) / t + T * e * act
                + 3 * T * hd * act / t, (prev,))
        # paged ragged attention: QK^T + PV over each lane's context,
        # streaming the head shard of the KV pages (+ scale rows on
        # quantized pools)
        kv_bytes = 2 * T * ctx * (hd / t) * arch.kv_itemsize
        if arch.kv_scales:
            kv_bytes += 2 * T * ctx * (h / t) * 4.0
        compute(f"l{i}.attn", 4 * T * ctx * hd / t, kv_bytes,
                (f"l{i}.qkv",))
        # head-row-parallel wo: partial sums complete in the all-reduce
        compute(f"l{i}.wo", 2 * T * hd * e / t + lora_wo,
                (hd * e * p) / t + T * e * act, (f"l{i}.attn",))
        all_reduce(f"l{i}.ar_attn", T * e * act, (f"l{i}.wo",))
        # column->row-parallel FFN, one all-reduce before the bias
        compute(f"l{i}.ffn", 2 * 2 * T * e * f / t + lora_ffn,
                (2 * e * f * p) / t + 2 * T * e * act,
                (tasks[-1].name,))
        all_reduce(f"l{i}.ar_ffn", T * e * act, (f"l{i}.ffn",))
        prev = tasks[-1].name
    # vocab-column-sharded head + the program's only all-gather
    compute("head", 2 * T * e * arch.vocab / t,
            (e * arch.vocab * p) / t + T * e * act, (prev,))
    if t > 1:
        tasks.append(ServeTask(
            "logits_gather", "collective",
            mm.all_gather(T * arch.vocab * act, t, axis), ("head",)))
    if transfer_tokens > 0:
        tasks.append(ServeTask(
            "kv_handoff", "transfer",
            mm.host_transfer(kv_handoff_bytes(arch,
                                              int(transfer_tokens))),
            ()))
    return tasks


def serve_device_bytes(arch: ServeArch, tensor_parallel: int) -> float:
    """Per-device resident bytes under head/vocab sharding: the weight
    shard plus each decode lane's context KV shard plus the LoRA
    adapter pool — what the memory penalty (and the auto placement's
    HBM fit) sees. The adapter term mirrors AdapterConfig.
    pool_device_bytes (serve/adapters.py): per slot, the replicated
    A / output-B factors plus the head/ff-sharded factors over t, at
    the activation itemsize, plus the f32 scale."""
    t = max(1, int(tensor_parallel))
    kv = (2 * arch.decode_lanes * arch.context
          * (arch.num_heads * arch.head_dim / t) * arch.num_layers
          * arch.kv_itemsize)
    if arch.kv_scales:
        kv += (2 * arch.decode_lanes * arch.context
               * (arch.num_heads / t) * arch.num_layers * 4.0)
    adapters = 0.0
    r = max(0, int(arch.adapter_rank))
    if r > 0 and arch.adapter_slots > 0:
        e, f = arch.hidden, arch.ff_dim
        hd = arch.num_heads * arch.head_dim
        rep = arch.num_layers * (3 * e * r + 3 * r * e)
        shd = arch.num_layers * (3 * r * hd + hd * r + r * f + f * r)
        adapters = arch.adapter_slots * (
            (rep + shd / t) * arch.act_itemsize + 4.0)
    return arch.weight_bytes() / t + kv + adapters
