"""Persistent per-op cost cache for the strategy search.

The reference keeps its measurement cache alive for exactly one search
run (hash-keyed in-memory map, simulator.cc:301-321); every new process
re-measures. Here the simulator's per-(op, op-strategy) costs — analytic
roofline numbers and, with FFConfig.measure_top_ops, measured-grounded
ones — are serialized to disk keyed by

    (op signature, shard/axis-map signature, machine-model fingerprint)

so repeated searches, `enumerate_mesh_shapes` sweeps, and tools
(sim_validation, search_bench) skip re-deriving and re-measuring costs
entirely. The machine-model fingerprint covers the MachineSpec numbers,
calibrated efficiency factors, torus/DCN layout, and mesh shape: any
change to what the cost formulas would see invalidates the entries
(stale entries for other fingerprints are kept in the file, not used).

Path: ~/.cache/flexflow_tpu/costcache.json by default (root overridable
via FLEXFLOW_TPU_CACHE like the calibration caches, file overridable
via FFConfig.cost_cache_file / --cost-cache). One CostCache object per
path is shared process-wide — parallel annealing chains read and write
the same store under a lock.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Dict, Optional

# row layout of a persisted OpCost; adding a field widens the row, and
# get()'s length check makes every pre-widening row a clean miss (the
# COST_MODEL_VERSION bump in the fingerprint retires them anyway)
_COST_FIELDS = ("fwd", "bwd", "fwd_comm", "bwd_comm", "sync", "mem",
                "update", "sync_bytes")


_PRICING_SRC_HASH: Optional[str] = None


def _pricing_source_hash() -> str:
    """Hash of the pricing-code sources (cost_model, machine_model,
    op_measure): an edited cost formula changes the fingerprint
    automatically, so stale cache entries can never be served by a
    forgotten COST_MODEL_VERSION bump. Memoized per process."""
    global _PRICING_SRC_HASH
    if _PRICING_SRC_HASH is None:
        h = hashlib.sha256()
        base = os.path.dirname(os.path.abspath(__file__))
        for mod in ("cost_model.py", "machine_model.py",
                    "op_measure.py", "serve_place.py"):
            try:
                with open(os.path.join(base, mod), "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(mod.encode())  # zipped install: name only
        _PRICING_SRC_HASH = h.hexdigest()[:16]
    return _PRICING_SRC_HASH


def machine_fingerprint(mm, mesh=None, precision=None,
                        overlap=None, serve=None) -> str:
    """Stable short hash of everything the cost formulas read from the
    machine model + mesh (plus the pricing code itself). Shared by the
    cost cache, sim_validation and perf_report so committed numbers are
    attributable to one machine state without re-measuring it.

    `precision` is the (compute_dtype, param_dtype) policy the costs
    were priced under (cost_model.op_precision): a dtype flip changes
    every byte/flops figure, so entries cached for f32 pricing must
    MISS for a bf16 search (and vice versa) — regression-tested in
    tests/test_mixed_precision.py. Per-dtype efficiency factors
    ("matmul:float32") ride the efficiency dict already hashed here.

    `overlap` is the runtime's sync-overlap configuration the simulator
    priced under — (search_overlap_backward_sync, grad_bucket_mb), see
    Simulator.overlap_sig(): an overlap flip or a bucket-size change
    alters every simulated makespan the cached numbers feed, so it must
    be a guaranteed cache miss (regression-tested in
    tests/test_overlap.py).

    `serve` is the serve-placement signature (search/serve_place:
    tensor degree, axis assignment, KV/activation dtypes) the serve
    pricing ran under: a placement or page-dtype flip changes the KV
    streaming and collective bytes of every serve-step cost, so cached
    serve entries must MISS across it (tests/test_serve_shard.py)."""
    from .cost_model import COST_MODEL_VERSION
    spec = {f.name: getattr(mm.spec, f.name, None)
            for f in dataclasses.fields(mm.spec)}
    blob = {
        "costmodel_v": COST_MODEL_VERSION,
        "pricing_src": _pricing_source_hash(),
        "spec": {k: (list(v) if isinstance(v, tuple) else v)
                 for k, v in spec.items()},
        "efficiency": dict(sorted(mm.efficiency.items())),
        "dtype_flops_scale": dict(sorted(
            getattr(mm, "dtype_flops_scale", {}).items())),
        "dcn_axes": list(mm.dcn_axes),
        "axis_topology": {k: list(v)
                          for k, v in sorted(mm.axis_topology.items())},
        "mesh": (sorted(mesh.shape.items()) if mesh is not None else None),
        "precision": (list(str(p) for p in precision)
                      if precision is not None else None),
        "overlap": (list(overlap) if overlap is not None else None),
        "serve": (list(serve) if serve is not None else None),
    }
    raw = json.dumps(blob, sort_keys=True, default=str)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def default_path() -> str:
    root = os.environ.get(
        "FLEXFLOW_TPU_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "flexflow_tpu"))
    return os.path.join(root, "costcache.json")


class CostCache:
    """Disk-backed {entry key -> OpCost} map, scoped to one machine
    fingerprint. Pipeline-expanded costs (OpCost.pipeline) carry nested
    schedule state and are never persisted."""

    _open: Dict[str, "CostCache"] = {}
    _open_lock = threading.Lock()

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        # fingerprint -> {key -> [len(_COST_FIELDS) floats]}
        self._data: Dict[str, Dict[str, list]] = {}
        self._dirty = False
        self._loaded = False
        self.hits = 0
        self.misses = 0

    @classmethod
    def open(cls, path: Optional[str] = None) -> "CostCache":
        """Process-wide shared instance per path (parallel chains and
        mesh-shape sweeps must see one read-mostly store)."""
        path = path or default_path()
        with cls._open_lock:
            if path not in cls._open:
                cls._open[path] = cls(path)
            return cls._open[path]

    # ---- keying ----
    @staticmethod
    def entry_key(op_sig: str, axis_sig, extra=()) -> str:
        raw = json.dumps([op_sig, list(axis_sig), list(extra)],
                         default=str)
        return hashlib.sha256(raw.encode()).hexdigest()[:24]

    # ---- I/O ----
    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self.path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return             # no cache yet — the common first run
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            # a corrupted / truncated store (crash mid-write on an old
            # build, disk fault, manual edit) must never crash a
            # search: warn, start empty, and let the next flush()
            # REBUILD the file wholesale (see flush's corrupt-merge
            # path). The cache is a pure accelerator — losing it costs
            # re-derivation, never correctness.
            import warnings
            warnings.warn(
                f"cost cache {self.path} is unreadable "
                f"({type(e).__name__}: {e}); rebuilding it from scratch")
            self._dirty = True   # next flush overwrites the wreck
            return
        if isinstance(data, dict):
            # row-level validation happens in get() (len check); here
            # just drop structurally-foreign subtrees
            self._data = {fp: dict(entries)
                          for fp, entries in data.items()
                          if isinstance(entries, dict)}

    def get(self, fingerprint: str, key: str):
        from .cost_model import OpCost
        with self._lock:
            self._ensure_loaded()
            row = self._data.get(fingerprint, {}).get(key)
            if row is None or len(row) != len(_COST_FIELDS):
                self.misses += 1
                return None
            self.hits += 1
            return OpCost(**{f: float(v)
                             for f, v in zip(_COST_FIELDS, row)})

    def put(self, fingerprint: str, key: str, cost) -> None:
        if cost.pipeline is not None:
            return
        with self._lock:
            self._ensure_loaded()
            self._data.setdefault(fingerprint, {})[key] = [
                float(getattr(cost, f)) for f in _COST_FIELDS]
            self._dirty = True

    def flush(self) -> None:
        """Atomic write (tmp + rename), merging entries another process
        may have written since we loaded. Unwritable cache paths never
        abort a search (same policy as measure.py)."""
        with self._lock:
            if not self._dirty:
                return
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                merged = {}
                try:
                    with open(self.path) as f:
                        on_disk = json.load(f)
                    if isinstance(on_disk, dict):
                        merged = {fp: e for fp, e in on_disk.items()
                                  if isinstance(e, dict)}
                except FileNotFoundError:
                    pass
                except (OSError, json.JSONDecodeError,
                        UnicodeDecodeError):
                    # corrupt on-disk store: do not merge garbage —
                    # this flush rewrites it wholesale from the
                    # in-memory entries (the rebuild _ensure_loaded
                    # promised)
                    import warnings
                    warnings.warn(
                        f"cost cache {self.path} was corrupt at flush; "
                        f"overwriting with this process's entries")
                for fp, entries in self._data.items():
                    merged.setdefault(fp, {}).update(entries)
                # the shared temp-then-os.replace primitive: a kill
                # mid-flush leaves the previous complete store, never
                # a truncation (and "cache.commit" is a stageable
                # chaos kill point like ckpt.commit/loader.commit)
                from ..core.checkpoint import atomic_write_json
                atomic_write_json(self.path, merged,
                                  fault_site="cache.commit")
                self._dirty = False
            except OSError:
                pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            n = sum(len(v) for v in self._data.values())
            return {"hits": self.hits, "misses": self.misses,
                    "entries": n}
