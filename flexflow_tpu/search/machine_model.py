"""TPU machine model: analytic costs for compute, HBM, and collectives.

Replaces the reference `MachineModel` hierarchy (include/simulator.h:99-236,
machine_model.cc — membus/UPI/NIC/PCIe/NVLink paths with per-segment
pipelining). On TPU the comm fabric collapses to two tiers: ICI (intra-pod
torus) and DCN (cross-slice); GSPMD's collectives have closed-form cost on
a ring/torus, so `get_comm_path` becomes per-collective formulas.

Calibration: `efficiency` factors default to typical XLA/TPU achieved
fractions and can be overwritten from real microbenchmarks
(search/measure.py) — the analog of the reference timing real kernels in
`measure_operator_cost`.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional

from ..parallel.mesh import MachineSpec


@dataclasses.dataclass
class TPUMachineModel:
    spec: MachineSpec
    # achieved-fraction calibration knobs (overridable via measure.py)
    efficiency: Dict[str, float] = dataclasses.field(default_factory=lambda: {
        "matmul": 0.55,      # MXU-bound ops (dense/attention GEMMs)
        "conv": 0.45,        # conv MXU fraction (im2col/layout overheads
        #                      put it below big-GEMM; MEASURED on device
        #                      by measure.py, reference conv_2d.cu:173-260
        #                      measures per-shape algorithms)
        "elementwise": 0.8,  # HBM-bound ops (fraction of peak HBM bw)
        "collective": 0.75,  # fraction of peak ICI bw
    })
    # per-dtype MXU rate relative to spec.peak_flops (which is the
    # bf16 basis — TPU datasheets quote bf16): f32 matmuls run at half
    # the bf16 rate (one MXU pass per f32 operand pair vs packed bf16),
    # f16 matches bf16. Overridable per machine file / calibration.
    dtype_flops_scale: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {
            "bfloat16": 1.0, "float16": 1.0, "float32": 0.5})
    # mesh axes that ride DCN instead of ICI (multi-host `data` axis)
    dcn_axes: tuple = ()
    # mesh axis -> tuple of physical torus dims it spans (from
    # assign_axis_topology); {} = flat (one ring per axis). A k-dim
    # axis runs ring phases over k link sets concurrently, and
    # all-to-all is bisection-bound by its LARGEST dim — the TPU form
    # of the reference's physical comm paths (machine_model.cc:695).
    axis_topology: Dict[str, tuple] = dataclasses.field(
        default_factory=dict)

    def _phys(self, axis: Optional[str], axis_size: int):
        """(k concurrent link sets, largest physical dim) for an axis.
        DCN axes are switched, not tori — always flat."""
        dims = (self.axis_topology.get(axis)
                if axis and axis not in self.dcn_axes else None)
        if not dims:
            return 1, axis_size
        return len(dims), max(dims)

    # ---- compute ----
    def peak_flops_for(self, dtype: Optional[str] = None) -> float:
        """Peak MXU rate for a compute dtype. None keeps the raw
        spec.peak_flops (bf16 basis) — the pre-precision-policy
        behavior callers outside op_cost still rely on."""
        if dtype is None:
            return self.spec.peak_flops
        return self.spec.peak_flops * self.dtype_flops_scale.get(
            str(dtype), 1.0)

    def _eff(self, key: str, dtype: Optional[str]) -> float:
        """Per-family efficiency with an optional per-dtype override:
        "matmul:float32" (written by measure.calibrate's per-dtype
        pass) beats the family factor "matmul"."""
        base = self.efficiency.get(key, self.efficiency["matmul"])
        if dtype is None:
            return base
        return self.efficiency.get(f"{key}:{dtype}", base)

    def compute_time(self, flops: float, bytes_moved: float,
                     is_matmul: bool = True,
                     kind: Optional[str] = None,
                     dtype: Optional[str] = None) -> float:
        """Roofline: max of MXU time and HBM time. `kind` selects a
        measured per-family MXU efficiency ("conv" today); default is
        the big-GEMM factor. `dtype` prices the op at that compute
        dtype's peak rate and (when calibrated) its measured per-dtype
        efficiency — the cost-model half of the mixed-precision policy
        (callers scale `bytes_moved` by the dtype itemsize themselves,
        cost_model.op_cost)."""
        eff = self._eff(kind if kind is not None else "matmul", dtype)
        t_flops = flops / (self.peak_flops_for(dtype) * eff)
        t_mem = bytes_moved / (self.spec.hbm_bandwidth
                               * self.efficiency["elementwise"])
        return max(t_flops, t_mem)

    # ---- collectives (ring formulas over the relevant axis) ----
    def _bw_lat(self, axis: Optional[str]):
        if axis is not None and axis in self.dcn_axes:
            # shared-NIC congestion: every chip on the host funnels its
            # cross-host traffic through one NIC (reference
            # EnhancedMachineModel congestion, machine_model.cc:172+)
            sharers = max(1, self.spec.chips_per_host)
            return (self.spec.dcn_bandwidth / sharers,
                    self.spec.dcn_latency)
        return (self.spec.ici_bandwidth * self.efficiency["collective"],
                self.spec.ici_latency)

    def _ring_bw_mult(self, axis: Optional[str], k: int) -> float:
        """Bandwidth multiplier for ring collectives: k concurrent link
        sets on a torus; a line (no wraparound) cannot close the ring,
        so the bidirectional algorithm degrades to ~half the torus
        bandwidth (ICI only — DCN is switched)."""
        if axis is not None and axis in self.dcn_axes:
            return 1.0
        wrap = 1.0 if self.spec.ici_wraparound else 0.5
        return k * wrap

    def all_reduce(self, nbytes: float, axis_size: int,
                   axis: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        bw, lat = self._bw_lat(axis)
        k, dmax = self._phys(axis, axis_size)
        # k-dim torus: per-dim ring phases run over disjoint link sets
        # concurrently -> k x bandwidth; latency chain follows the
        # LONGEST dim's ring (other dims' hops overlap it)
        mult = self._ring_bw_mult(axis, k)
        return 2.0 * (axis_size - 1) / axis_size * nbytes / (bw * mult) \
            + 2 * (dmax - 1) * lat

    def all_gather(self, nbytes_out: float, axis_size: int,
                   axis: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        bw, lat = self._bw_lat(axis)
        k, dmax = self._phys(axis, axis_size)
        mult = self._ring_bw_mult(axis, k)
        return (axis_size - 1) / axis_size * nbytes_out / (bw * mult) \
            + (dmax - 1) * lat

    reduce_scatter = all_gather  # same ring cost

    def all_to_all(self, nbytes_local: float, axis_size: int,
                   axis: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        bw, lat = self._bw_lat(axis)
        k, dmax = self._phys(axis, axis_size)
        # bisection-bound: total V_local*n/4 bytes cross the worst cut;
        # a torus cut perpendicular to the largest dim has 2*n/dmax
        # (wraparound) link pairs -> T = V_local * dmax / (8 * bw) per
        # direction-pair; a line (no wraparound) halves the cut. The
        # old (n-1)/n ring formula underpriced large-n all-to-alls by
        # ~n/4 (EP dispatch misranking).
        wrap = 2.0 if self.spec.ici_wraparound else 1.0
        if axis is not None and axis in self.dcn_axes:
            # DCN is switched, not a torus: the NIC serializes the
            # (n-1)/n exchange — keep the flat formula
            return (axis_size - 1) / axis_size * nbytes_local / bw \
                + (axis_size - 1) * lat
        # worst-case hop distance: dmax/2 around a torus ring, dmax
        # end-to-end on a line
        hops = dmax / 2 if self.spec.ici_wraparound else dmax
        return nbytes_local * dmax / (4.0 * wrap * bw) + hops * lat

    def ppermute(self, nbytes: float, axis: Optional[str] = None) -> float:
        bw, lat = self._bw_lat(axis)
        return nbytes / bw + lat

    # ---- host link (disaggregated serving's page-handoff path) ----
    def host_transfer(self, nbytes: float) -> float:
        """Seconds to move `nbytes` over the chip<->host DMA link — the
        path a prefill engine ships finished KV pages over to a decode
        engine (serve/disagg.py). Priced like ppermute on the host-link
        spec: the search's transfer term, so a KV-dtype flip (fewer
        bytes per page) changes the handoff cost it weighs a
        prefill:decode ratio against."""
        if nbytes <= 0:
            return 0.0
        bw = max(1.0, float(getattr(self.spec, "host_link_bandwidth",
                                    5e10)))
        lat = float(getattr(self.spec, "host_link_latency", 5e-6))
        return nbytes / bw + lat

    # ---- memory penalty (reference simulator.cc:603-628: 1ms per MB
    # over framebuffer capacity) ----
    def memory_penalty(self, bytes_per_device: float) -> float:
        over = bytes_per_device - self.spec.hbm_capacity
        if over <= 0:
            return 0.0
        return over * 1e-9  # 1 ms per MB, same constant as the reference

    # ---- calibration I/O ----
    def save_calibration(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.efficiency, f)

    def load_calibration(self, path: str) -> None:
        with open(path) as f:
            self.efficiency.update(json.load(f))


def assign_axis_topology(mesh, torus_dims: tuple,
                         dcn_axes: tuple = ()) -> Dict[str, tuple]:
    """Lay mesh axes out over the physical torus factorization, in mesh
    axis order (the standard TPU layout: contiguous torus dims per mesh
    axis). Each axis consumes whole torus dims while their product
    divides the axis size; an axis that cannot be covered exactly (or
    once dims run out) falls back to a single ring. DCN-resident axes
    span hosts, not ICI links — they consume no torus dims. Mirrors
    what jax.experimental.mesh_utils.create_device_mesh arranges
    physically."""
    out: Dict[str, tuple] = {}
    if mesh is None or not torus_dims:
        return out
    remaining = list(torus_dims)
    for name, size in mesh.shape.items():
        if name in dcn_axes:
            continue
        got: list = []
        prod = 1
        while remaining and prod < size and size % (
                prod * remaining[0]) == 0:
            prod *= remaining[0]
            got.append(remaining.pop(0))
        if prod == size and got:
            out[name] = tuple(got)
        else:
            # not exactly coverable: restore and price as one ring
            remaining = got + remaining
    return out


def default_machine_model(mesh=None, spec: Optional[MachineSpec] = None,
                          machine_file: Optional[str] = None
                          ) -> TPUMachineModel:
    """Build a model for the current device (v5e single chip by default).
    `machine_file` (FFConfig.machine_model_file) may override MachineSpec
    fields via JSON — the analog of the reference's machine config file
    (machine_config_example). A multi-host run marks the mesh's `data`
    axis as DCN-resident (cross-slice collectives priced at DCN rates)."""
    user_spec = spec is not None
    if spec is None:
        spec = MachineSpec.v5e()
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
            if "v5p" in kind or "v4" in kind:
                spec = MachineSpec()
        except Exception:
            pass
    file_keys = set()
    file_data: Dict = {}
    if machine_file:
        with open(machine_file) as f:
            file_data = json.load(f)
        for k, v in file_data.items():
            if hasattr(spec, k):
                setattr(spec, k, v)
                file_keys.add(k)
    dcn_axes = ()
    if mesh is not None:
        spec.num_chips = int(mesh.size)
        try:
            import jax
            if jax.process_count() > 1 and "data" in mesh.shape:
                dcn_axes = ("data",)
                # autodetected topology must not clobber an explicit
                # value — from the machine file OR a caller-built spec
                if "chips_per_host" not in file_keys and not user_spec:
                    spec.chips_per_host = max(1, jax.local_device_count())
        except Exception:
            pass
    # physical-torus layout: machine-file per-axis pins
    # ({"axis_topology": {"data": [4, 4]}}) fully govern the axes they
    # mention — a pin dropped as invalid leaves THAT axis flat-ring, as
    # warned; axes the file does not mention derive from
    # spec.ici_torus_dims ({"ici_torus_dims": [4, 4, 4]}) when set
    pins: Dict[str, tuple] = {}
    pinned_axes: tuple = ()
    if "axis_topology" in file_data:
        raw = {k: tuple(v) for k, v in file_data["axis_topology"].items()}
        pinned_axes = tuple(raw)  # dropped pins stay excluded (= flat)
        import math
        import warnings
        for name, dims in raw.items():
            size = mesh.shape.get(name) if mesh is not None else None
            if size is not None and math.prod(dims) != size:
                warnings.warn(
                    f"machine file axis_topology[{name!r}]={dims} "
                    f"does not factor the mesh axis size {size}; "
                    f"ignoring the pin (flat-ring pricing)")
            else:
                pins[name] = dims
    # pins occupy physical dims: remove them (by multiset) from the
    # pool before deriving the unmentioned axes, or two mesh axes could
    # be priced on the same physical ICI dimension
    pool = list(getattr(spec, "ici_torus_dims", ()) or ())
    for dims in pins.values():
        for d in dims:
            if d in pool:
                pool.remove(d)
    derived = assign_axis_topology(mesh, tuple(pool),
                                   dcn_axes + pinned_axes)
    return TPUMachineModel(spec=spec, dcn_axes=dcn_axes,
                           axis_topology={**derived, **pins})
