"""TPU machine model: analytic costs for compute, HBM, and collectives.

Replaces the reference `MachineModel` hierarchy (include/simulator.h:99-236,
machine_model.cc — membus/UPI/NIC/PCIe/NVLink paths with per-segment
pipelining). On TPU the comm fabric collapses to two tiers: ICI (intra-pod
torus) and DCN (cross-slice); GSPMD's collectives have closed-form cost on
a ring/torus, so `get_comm_path` becomes per-collective formulas.

Calibration: `efficiency` factors default to typical XLA/TPU achieved
fractions and can be overwritten from real microbenchmarks
(search/measure.py) — the analog of the reference timing real kernels in
`measure_operator_cost`.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional

from ..parallel.mesh import MachineSpec


@dataclasses.dataclass
class TPUMachineModel:
    spec: MachineSpec
    # achieved-fraction calibration knobs (overridable via measure.py)
    efficiency: Dict[str, float] = dataclasses.field(default_factory=lambda: {
        "matmul": 0.55,      # MXU-bound ops (dense/attention GEMMs)
        "conv": 0.45,        # conv MXU fraction (im2col/layout overheads
        #                      put it below big-GEMM; MEASURED on device
        #                      by measure.py, reference conv_2d.cu:173-260
        #                      measures per-shape algorithms)
        "elementwise": 0.8,  # HBM-bound ops (fraction of peak HBM bw)
        "collective": 0.75,  # fraction of peak ICI bw
    })
    # mesh axes that ride DCN instead of ICI (multi-host `data` axis)
    dcn_axes: tuple = ()

    # ---- compute ----
    def compute_time(self, flops: float, bytes_moved: float,
                     is_matmul: bool = True,
                     kind: Optional[str] = None) -> float:
        """Roofline: max of MXU time and HBM time. `kind` selects a
        measured per-family MXU efficiency ("conv" today); default is
        the big-GEMM factor."""
        eff = self.efficiency["matmul"]
        if kind is not None:
            eff = self.efficiency.get(kind, eff)
        t_flops = flops / (self.spec.peak_flops * eff)
        t_mem = bytes_moved / (self.spec.hbm_bandwidth
                               * self.efficiency["elementwise"])
        return max(t_flops, t_mem)

    # ---- collectives (ring formulas over the relevant axis) ----
    def _bw_lat(self, axis: Optional[str]):
        if axis is not None and axis in self.dcn_axes:
            # shared-NIC congestion: every chip on the host funnels its
            # cross-host traffic through one NIC (reference
            # EnhancedMachineModel congestion, machine_model.cc:172+)
            sharers = max(1, self.spec.chips_per_host)
            return (self.spec.dcn_bandwidth / sharers,
                    self.spec.dcn_latency)
        return (self.spec.ici_bandwidth * self.efficiency["collective"],
                self.spec.ici_latency)

    def all_reduce(self, nbytes: float, axis_size: int,
                   axis: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        bw, lat = self._bw_lat(axis)
        return 2.0 * (axis_size - 1) / axis_size * nbytes / bw \
            + 2 * (axis_size - 1) * lat

    def all_gather(self, nbytes_out: float, axis_size: int,
                   axis: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        bw, lat = self._bw_lat(axis)
        return (axis_size - 1) / axis_size * nbytes_out / bw \
            + (axis_size - 1) * lat

    reduce_scatter = all_gather  # same ring cost

    def all_to_all(self, nbytes_local: float, axis_size: int,
                   axis: Optional[str] = None) -> float:
        if axis_size <= 1:
            return 0.0
        bw, lat = self._bw_lat(axis)
        # each device exchanges (n-1)/n of its local bytes
        return (axis_size - 1) / axis_size * nbytes_local / bw \
            + (axis_size - 1) * lat

    def ppermute(self, nbytes: float, axis: Optional[str] = None) -> float:
        bw, lat = self._bw_lat(axis)
        return nbytes / bw + lat

    # ---- memory penalty (reference simulator.cc:603-628: 1ms per MB
    # over framebuffer capacity) ----
    def memory_penalty(self, bytes_per_device: float) -> float:
        over = bytes_per_device - self.spec.hbm_capacity
        if over <= 0:
            return 0.0
        return over * 1e-9  # 1 ms per MB, same constant as the reference

    # ---- calibration I/O ----
    def save_calibration(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.efficiency, f)

    def load_calibration(self, path: str) -> None:
        with open(path) as f:
            self.efficiency.update(json.load(f))


def default_machine_model(mesh=None, spec: Optional[MachineSpec] = None,
                          machine_file: Optional[str] = None
                          ) -> TPUMachineModel:
    """Build a model for the current device (v5e single chip by default).
    `machine_file` (FFConfig.machine_model_file) may override MachineSpec
    fields via JSON — the analog of the reference's machine config file
    (machine_config_example). A multi-host run marks the mesh's `data`
    axis as DCN-resident (cross-slice collectives priced at DCN rates)."""
    user_spec = spec is not None
    if spec is None:
        spec = MachineSpec.v5e()
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
            if "v5p" in kind or "v4" in kind:
                spec = MachineSpec()
        except Exception:
            pass
    file_keys = set()
    if machine_file:
        with open(machine_file) as f:
            data = json.load(f)
        for k, v in data.items():
            if hasattr(spec, k):
                setattr(spec, k, v)
                file_keys.add(k)
    dcn_axes = ()
    if mesh is not None:
        spec.num_chips = int(mesh.size)
        try:
            import jax
            if jax.process_count() > 1 and "data" in mesh.shape:
                dcn_axes = ("data",)
                # autodetected topology must not clobber an explicit
                # value — from the machine file OR a caller-built spec
                if "chips_per_host" not in file_keys and not user_spec:
                    spec.chips_per_host = max(1, jax.local_device_count())
        except Exception:
            pass
    return TPUMachineModel(spec=spec, dcn_axes=dcn_axes)
