"""Event-driven execution simulator.

Direct analog of the reference `Simulator::simulate_runtime`
(simulator.cc:330-629): build a task graph (fwd, bwd, comm, update nodes)
for a candidate global strategy and run a priority-queue event loop over
contended resources. On TPU the resources are the (single, SPMD) compute
stream and the ICI fabric; comm tasks overlap compute exactly as XLA's
async collectives do, and the DP gradient all-reduce can overlap the
remaining backward pass (the reference models the same overlap for PS
update, simulator.cc:393-497, gated by
`FFConfig.search_overlap_backward_sync`).

When the runtime's bucketed grad sync is on (FFConfig.grad_bucket_mb >
0, core/overlap.py), the sync tasks mirror the EXECUTED structure:
per-op sync tasks go zero-duration and one bucket-granular sync task
per bucket (same walk-order partition the executor tags) prices ONE
combined all-reduce of the bucket's summed per-device payload —
real per-bucket latency+bandwidth from the machine model — depending on
its members' backward tasks, not the whole backward. The search
therefore rewards exactly the overlap the executor delivers.

Memory over HBM capacity adds the reference's 1ms/MB penalty
(simulator.cc:603-628, machine_model.memory_penalty).
"""

from __future__ import annotations

import dataclasses
import functools
import heapq
from typing import Dict, List, Optional

from ..parallel.pconfig import Strategy
from .cost_model import OpCost, op_cost
from .machine_model import TPUMachineModel, default_machine_model


@functools.lru_cache(maxsize=256)
def _schedule_tables(n_dev: int, v: int, M: int):
    """Memoized 1F1B/interleaved schedule tables (pure function of the
    triple; the annealing loop reprices thousands of candidates)."""
    from ..parallel.graph_pipeline import interleaved_schedule
    return interleaved_schedule(n_dev, v, M)


@dataclasses.dataclass
class SimTask:
    name: str
    duration: float
    resource: object            # one hashable key ("compute"/"comm"/
    # ("stage", u, k)) or a LIST of keys the task occupies simultaneously
    # (a placed op's device set; an SPMD op holding every device)
    deps: List["SimTask"] = dataclasses.field(default_factory=list)
    # runtime state
    unresolved: int = 0
    ready_time: float = 0.0
    finish_time: float = 0.0
    # schedule recording (simulate(record=True) only): the task's
    # scheduled start (the event loop's exact float, NOT finish -
    # duration, which re-rounds) and what bound it — the dep that set
    # its ready time, or the previous occupant of its resource —
    # walked backward for the critical path
    start_time: float = 0.0
    blocker: object = None
    ready_by: object = None


class TaskGraph:
    def __init__(self):
        self.tasks: List[SimTask] = []

    def add(self, name, duration, resource, deps=()):
        t = SimTask(name=name, duration=duration, resource=resource,
                    deps=list(deps))
        self.tasks.append(t)
        return t

    def simulate(self, record: bool = False) -> float:
        """Priority-queue event loop (reference simulator.cc:499-554).
        A task may occupy several resources at once (tuple resource) —
        this is how per-device concurrency is modeled: ops bound to
        disjoint device sets proceed in parallel, overlapping sets
        serialize (reference: per-device task queues in slice_task).

        ``record=True`` additionally stamps each task's binding
        constraint (``blocker``: the dep that set its ready time, or
        the resource's previous occupant when the task waited on the
        resource instead) so :meth:`critical_path` can walk the chain
        that determined the makespan. The recording branch is gated so
        the annealing hot path pays nothing for it."""
        children: Dict[int, List[SimTask]] = {}
        for t in self.tasks:
            t.unresolved = len(t.deps)
            for d in t.deps:
                children.setdefault(id(d), []).append(t)
        free: Dict[object, float] = {}
        last_occupant: Dict[object, SimTask] = {}
        counter = 0
        q = []
        for t in self.tasks:
            if t.unresolved == 0:
                heapq.heappush(q, (t.ready_time, counter, t))
                counter += 1
        makespan = 0.0
        done = 0
        while q:
            ready, _, t = heapq.heappop(q)
            if t.duration == 0.0:
                # zero-duration tasks are transparent: they neither
                # consult nor occupy their resource. Provably identical
                # to the occupy-path for every graph this file builds
                # (a zero-duration task can never raise free[k] above
                # any later pop's ready time, since pops are ordered by
                # ready time), and it makes a materialized zero-cost
                # comm/sync task exactly equivalent to no task — the
                # invariant the delta-simulation template relies on.
                t.finish_time = ready
                if record:
                    t.start_time = ready
                    t.blocker = t.ready_by
            else:
                keys = t.resource if isinstance(t.resource, list) \
                    else (t.resource,)
                start = max([ready] + [free.get(k, 0.0) for k in keys])
                t.finish_time = start + t.duration
                if record:
                    t.start_time = start
                    t.blocker = t.ready_by
                    if start > ready or t.ready_by is None:
                        for k in keys:
                            if free.get(k, 0.0) == start \
                                    and k in last_occupant:
                                t.blocker = last_occupant[k]
                                break
                    for k in keys:
                        last_occupant[k] = t
                for k in keys:
                    free[k] = t.finish_time
            makespan = max(makespan, t.finish_time)
            done += 1
            for c in children.get(id(t), []):
                if t.finish_time >= c.ready_time:
                    c.ready_time = t.finish_time
                    if record:
                        c.ready_by = t
                c.unresolved -= 1
                if c.unresolved == 0:
                    heapq.heappush(q, (c.ready_time, counter, c))
                    counter += 1
        assert done == len(self.tasks), "cycle in task graph"
        return makespan

    def critical_path(self) -> set:
        """ids of the tasks on the chain that determined the makespan
        (valid after simulate(record=True)): start at the last-finishing
        task and walk each task's binding constraint backward."""
        if not self.tasks:
            return set()
        t = max(self.tasks, key=lambda x: x.finish_time)
        crit = set()
        while t is not None and id(t) not in crit:
            crit.add(id(t))
            t = t.blocker
        return crit

    def export_dot(self, path: str) -> None:
        """Taskgraph DOT export (reference --taskgraph, simulator.h DotFile)."""
        with open(path, "w") as f:
            f.write("digraph taskgraph {\n")
            ids = {id(t): i for i, t in enumerate(self.tasks)}
            for t in self.tasks:
                f.write(f'  t{ids[id(t)]} [label="{t.name}\\n'
                        f'{t.duration*1e6:.1f}us ({t.resource})"];\n')
            for t in self.tasks:
                for d in t.deps:
                    f.write(f"  t{ids[id(d)]} -> t{ids[id(t)]};\n")
            f.write("}\n")


def _axis_sig(s) -> tuple:
    """Hashable signature of one op's axis map — the in-memory cost-cache
    key and the delta template's change detector."""
    return tuple(sorted((k, str(v)) for k, v in s.axis_map.items()))


def _res_label(res) -> str:
    """Human label of one simulator resource key."""
    if isinstance(res, list):
        if "compute" in res:
            return "compute"
        return "dev " + ",".join(str(k[1]) for k in res)
    if isinstance(res, tuple):
        if res[0] == "dev":
            return f"dev {res[1]}"
        if res[0] == "stage":
            return f"{res[1]} stage {res[2]}"
        return " ".join(str(p) for p in res)
    return str(res)


def _res_track(res):
    """(process, thread) track of a simulator resource — one Perfetto
    row per contended resource, so a task's placement in the trace IS
    its placement in the event loop ("comm" renders as the ICI
    fabric row)."""
    if res == "comm":
        return ("sim", "ici")
    return ("sim", _res_label(res))


def op_edges(model):
    """(producer-map, producer->consumer op pairs) in canonical order:
    iteration over each op's inputs.  Every engine that walks the graph
    (this simulator, the Python MCMC loop, the native search lowering)
    MUST derive edges through this one function — backward-dependency
    construction and propagation moves depend on the exact order."""
    producer = {}
    for op in model.ops:
        for t in op.outputs:
            producer[t.uid] = op
    edges = []
    for op in model.ops:
        for t in op.inputs:
            if t.uid in producer:
                edges.append((producer[t.uid], op))
    return producer, edges


@dataclasses.dataclass
class _BuiltGraph:
    """One _build_graph result: the task graph plus the metadata the
    delta path needs to capture a reusable template."""
    graph: TaskGraph
    total_mem: float
    costs: Dict[str, OpCost]
    slots: Dict[str, Dict[str, SimTask]]   # op -> component -> task
    expanded: set                          # pipeline-expanded units
    placed: dict                           # device-placed units
    # bucketed grad sync (grad_bucket_mb > 0): member names per bucket
    # (walk order) and the bucket sync tasks, [] when off
    bucket_members: list = dataclasses.field(default_factory=list)
    bucket_tasks: list = dataclasses.field(default_factory=list)


_SLOT_NAMES = ("fwd_comm", "fwd", "bwd_comm", "bwd", "sync")


class _DeltaTemplate:
    """Flattened scheduled task graph for delta re-simulation (the
    paper's delta simulation algorithm: keep the task graph of the
    current strategy, re-cost only changed ops, re-run the event loop
    over the cached arrays instead of rebuilding anything). Replaying
    the heap loop over these arrays reproduces TaskGraph.simulate
    bit-for-bit — same tie-breaking, same zero-duration transparency —
    so the delta path is EXACT, not an approximation; the drift counter
    exists to prove that at runtime, not to paper over error."""

    __slots__ = ("durations", "children", "ndeps0", "roots", "res",
                 "n_res", "op_slots", "op_sig", "op_class", "op_mem",
                 "op_order", "op_sync_bytes", "bucket_of",
                 "bucket_members", "bucket_slot")


@dataclasses.dataclass
class _DeltaToken:
    """Result of one simulate_delta call: the simulated step seconds
    plus the undo record delta_reject applies when the move loses —
    (per-op splices, bucket-task splices)."""
    cost: float
    undo: tuple


class Simulator:
    def __init__(self, model, mesh, mm: Optional[TPUMachineModel] = None,
                 overlap_backward_sync: Optional[bool] = None):
        self.model = model
        self.mesh = mesh
        self.mm = mm or default_machine_model(mesh)
        # overlap modeling resolves from the config unless the caller
        # pins it (legacy constructor-only behavior): the SAME knob the
        # CLI exposes (--no-overlap-sync) so a flip reaches both the
        # task-graph shape and the cost-cache fingerprint below
        self._overlap_arg = overlap_backward_sync
        cfg = getattr(model, "config", None)
        self.overlap = (bool(getattr(cfg, "search_overlap_backward_sync",
                                     True))
                        if overlap_backward_sync is None
                        else bool(overlap_backward_sync))
        # the runtime's bucketed-sync config (core/overlap.py): priced
        # only under overlap (a serialized monolithic sync has no
        # buckets to hide). Resolved through the SAME resolve_bucket_mb
        # the executor uses (None = auto from the machine model for
        # this mesh), so the simulator prices the partition the
        # executor would actually deliver on this mesh and the cost
        # cache is keyed by the RESOLVED value (overlap_sig).
        from ..core.overlap import resolve_bucket_mb
        self.bucket_mb = resolve_bucket_mb(cfg, model, mesh=mesh)
        self._cache: Dict[tuple, OpCost] = {}
        # global multiplier calibrated from one real measured step
        # (calibrate_end_to_end); scales predictions without changing the
        # relative ordering the search depends on.
        self.time_scale = 1.0
        # calibrated fixed dispatch cost added once per simulated step
        # (strategy-independent; never changes the ranking)
        self.step_overhead = self.mm.efficiency.get("step_overhead_s", 0.0)
        # strategy-independent graph maps, built once (the annealing loop
        # calls simulate() thousands of times)
        self._producer, _ = op_edges(model)
        self._ops_by_name = {op.name: op for op in model.ops}
        # fused-unit partition + edges per strategy signature (fusion
        # groups depend only on each op's axis map)
        self._unit_cache: Dict[tuple, tuple] = {}
        # staged-pipeline candidate caches (previously created lazily via
        # getattr; proper __init__ state so invalidate() can clear them)
        self._balanced_cache: Dict[tuple, object] = {}
        self._staged_cost_cache: Dict[tuple, tuple] = {}
        self._staged_vstages = 1
        # delta-simulation template (simulate_delta); None until a
        # delta_rebase() established one for the current base strategy
        self._delta: Optional[_DeltaTemplate] = None
        # last record=True event-loop graph (export_schedule)
        self._last_graph: Optional[TaskGraph] = None
        # search instrumentation, rendered by profiling.search_report
        self.stats: Dict[str, int] = {
            "full_sims": 0, "delta_sims": 0, "delta_fallbacks": 0,
            "drift_resyncs": 0, "cost_mem_hits": 0, "cost_disk_hits": 0,
            "cost_computes": 0,
        }
        # persistent per-op cost cache, keyed by (op signature, axis-map
        # signature, machine-model fingerprint); shared process-wide
        cfg = getattr(model, "config", None)
        self._disk = None
        self._fingerprint = None
        if getattr(cfg, "search_cost_cache", True):
            from .cost_cache import CostCache, machine_fingerprint
            self._disk = CostCache.open(
                getattr(cfg, "cost_cache_file", None) or None)
            self._fingerprint = machine_fingerprint(
                self.mm, mesh, precision=self._precision(),
                overlap=self.overlap_sig())
        self._op_sig_memo: Dict[str, str] = {}
        self._cfg_sig = self._compute_cfg_sig()
        # per-op measured grounding (FFConfig.measure_top_ops)
        self._measured_set: set = self._choose_measured_ops()

    def overlap_sig(self):
        """(overlap flag, grad_bucket_mb) — the sync-overlap half of
        the machine fingerprint (cost_cache.machine_fingerprint); tools
        stamping fingerprints next to simulated numbers pass this so
        their stamps match the simulator's cache scope."""
        return (bool(self.overlap), float(self.bucket_mb))

    def _precision(self):
        """(compute_dtype, param_dtype) names of the model's policy —
        folded into the machine fingerprint so cached costs priced
        under one precision can never serve a search under another."""
        import jax.numpy as jnp
        cfg = getattr(self.model, "config", None)
        if cfg is None:
            return ("float32", "float32")
        return (jnp.dtype(getattr(cfg, "compute_dtype",
                                  jnp.float32)).name,
                jnp.dtype(getattr(cfg, "param_dtype", jnp.float32)).name)

    def _compute_cfg_sig(self) -> tuple:
        """Config/optimizer facts op_cost reads beyond the op + strategy
        (embedding sparse-update eligibility) — part of the persistent
        cache key so a flag flip can't resurrect stale entries."""
        cfg = getattr(self.model, "config", None)
        opt = getattr(self.model, "optimizer", None)
        mode = None
        if opt is not None:
            try:
                mode = opt.sparse_mode()
            except Exception:
                mode = None
        return (bool(getattr(cfg, "sparse_embedding_updates", True)),
                bool(getattr(cfg, "sparse_embedding_lazy", False)),
                str(mode)) + self._precision()

    def invalidate(self) -> None:
        """Drop every derived cache (op costs, fused units, staged
        tables, the delta template) — call after mutating the machine
        model, config cost knobs, or the optimizer. The persistent disk
        store is not cleared; entries are re-keyed via the fingerprint
        and config signature instead."""
        self._cache.clear()
        self._unit_cache.clear()
        self._balanced_cache.clear()
        self._staged_cost_cache.clear()
        self._delta = None
        self._op_sig_memo.clear()
        self._cfg_sig = self._compute_cfg_sig()
        cfg = getattr(self.model, "config", None)
        if self._overlap_arg is None:
            self.overlap = bool(getattr(
                cfg, "search_overlap_backward_sync", True))
        from ..core.overlap import resolve_bucket_mb
        self.bucket_mb = resolve_bucket_mb(cfg, self.model,
                                           mesh=self.mesh)
        if self._disk is not None:
            from .cost_cache import machine_fingerprint
            self._fingerprint = machine_fingerprint(
                self.mm, self.mesh, precision=self._precision(),
                overlap=self.overlap_sig())
        self._measured_set = self._choose_measured_ops()

    def flush_cost_cache(self) -> None:
        if self._disk is not None:
            self._disk.flush()

    def search_stats(self) -> Dict[str, object]:
        """Counter snapshot plus shared-cache state for search_report."""
        out: Dict[str, object] = dict(self.stats)
        if self._disk is not None:
            out["disk_cache"] = self._disk.stats()
            out["fingerprint"] = self._fingerprint
        ci = _schedule_tables.cache_info()
        out["schedule_tables"] = {
            "hits": ci.hits, "misses": ci.misses,
            "currsize": ci.currsize, "maxsize": ci.maxsize}
        return out

    def calibrate_end_to_end(self, strategy: Strategy,
                             measured_step_seconds: float) -> float:
        """Set time_scale so the *step-time* part of simulate(strategy)
        equals the measured step time (the memory penalty is excluded
        from scaling, and the calibrated fixed dispatch overhead is
        subtracted from the measurement first) — the TPU analog of the
        reference grounding its model in real kernel measurements.
        Returns the scale applied."""
        raw, _penalty = self._simulate_raw(strategy)
        if measured_step_seconds <= self.step_overhead:
            # overhead-bound step: subtracting would zero the scale and
            # make every strategy simulate identically — drop the
            # overhead split and scale against the whole measurement
            import warnings
            warnings.warn(
                f"measured step ({measured_step_seconds*1e6:.0f}us) is "
                f"within the calibrated dispatch overhead "
                f"({self.step_overhead*1e6:.0f}us); calibrating without "
                f"the overhead split")
            self.step_overhead = 0.0
        if raw > 0:
            self.time_scale = (measured_step_seconds
                               - self.step_overhead) / raw
        return self.time_scale

    def _op_cost(self, op, strategy: Strategy) -> OpCost:
        """Per-(op, op-strategy) cost with caching (the analog of the
        reference's hash-keyed measurement cache, simulator.cc:301-321).
        With FFConfig.measure_top_ops > 0, the top-N ops by analytic
        time get their fwd/bwd REPLACED by isolated-op jit measurements
        at the strategy's data-sharded sub-shape (op_measure.py — the
        reference's measure_operator_cost, model.cu:20-62); residual
        non-sample shardings still divide analytically.

        Three tiers: in-memory dict -> persistent disk store (keyed by
        op signature + axis map + machine fingerprint, cost_cache.py)
        -> compute. The disk tier is what lets repeated searches and
        mesh-shape sweeps in NEW processes skip re-deriving (and, under
        measure_top_ops, re-measuring) every cost."""
        s = strategy.for_op(op.name)
        return self._op_cost_for(op, s, _axis_sig(s))

    def _op_cost_for(self, op, s, sig) -> OpCost:
        key = (op.name, sig)
        c = self._cache.get(key)
        if c is not None:
            self.stats["cost_mem_hits"] += 1
            return c
        dkey = None
        if self._disk is not None:
            from .cost_cache import CostCache
            osig = self._op_sig_memo.get(op.name)
            if osig is None:
                from .op_measure import op_signature
                osig = self._op_sig_memo[op.name] = op_signature(op, 1)
            dkey = CostCache.entry_key(
                osig, sig,
                self._cfg_sig + (op.name in self._measured_set,))
            c = self._disk.get(self._fingerprint, dkey)
        if c is None:
            c = self.measured_adjust(op, s,
                                     op_cost(op, s, self.mesh, self.mm))
            self.stats["cost_computes"] += 1
            if dkey is not None:
                self._disk.put(self._fingerprint, dkey, c)
        else:
            self.stats["cost_disk_hits"] += 1
        self._cache[key] = c
        return c

    def measured_adjust(self, op, s, c: OpCost) -> OpCost:
        """Replace analytic fwd/bwd with measured seconds for grounded
        ops (measure_top_ops). Measurement happens at the sample-sharded
        sub-shape WHEN the sample axis genuinely divides; every other
        sharding axis divides the measured time analytically. Pipelined
        meta-ops and device-pinned ops keep their analytic expansion.
        Shared by the Python cache and the native engine's cost table
        (native_search.py) so both rank on the same grounded numbers."""
        if op.name not in self._measured_set or s.device_ids \
                or c.pipeline is not None:
            return c
        from .cost_model import compute_shards
        from .op_measure import CONV_CHAIN_TYPES, measure_op
        from ..parallel.pconfig import OpStrategy
        shards_total = compute_shards(op, s, self.mesh)
        s_nosample = OpStrategy({k: v for k, v in s.axis_map.items()
                                 if k != "sample"})
        resid = max(1, compute_shards(op, s_nosample, self.mesh))
        sample_div = max(1, shards_total // resid)
        m = measure_op(op, sample_shard=sample_div)
        if m is None:
            return c
        # conv-chain ops carry the per-device-kind in-situ correction:
        # isolated microbenchmarks under-predict in-graph conv cost
        # (op_measure.conv_in_situ_factor; VERDICT r4 #5)
        f = 1.0
        if op.op_type in CONV_CHAIN_TYPES:
            from .op_measure import conv_in_situ_factor
            f = conv_in_situ_factor()
        return dataclasses.replace(c, fwd=m["fwd"] * f / resid,
                                   bwd=m["bwd"] * f / resid)

    def _choose_measured_ops(self) -> set:
        """Ops covered by the top-N measurement SIGNATURES (shape
        classes) by aggregate analytic time. The cost cap is jit
        compiles, and measure_op memoizes per signature — so N
        signatures can ground far more than N ops (Inception's ~100
        convs share a handful of shapes; capping op count left most of
        the model analytic). Pipeline meta-ops are excluded: one timing
        of the whole stack would be the giant compile this cap exists
        to avoid, and it would drop the bubble factor."""
        n = int(getattr(self.model.config, "measure_top_ops", 0) or 0)
        if n <= 0:
            return set()
        from .op_measure import op_signature
        seed = Strategy()
        by_sig: Dict[str, list] = {}
        sig_time: Dict[str, float] = {}
        for op in self.model.ops:
            if op.op_type == "pipeline_blocks":
                continue
            c = op_cost(op, seed.for_op(op.name), self.mesh, self.mm)
            sig = op_signature(op, 1)
            by_sig.setdefault(sig, []).append(op.name)
            sig_time[sig] = sig_time.get(sig, 0.0) + c.fwd + c.bwd
        top = sorted(sig_time, key=sig_time.get, reverse=True)[:n]
        return {name for sig in top for name in by_sig[sig]}

    def _units_for(self, strategy: Strategy):
        """(groups, unit_deps, unit_consumers) for this strategy's fusion
        partition, cached on the per-op axis-map signature (the annealing
        loop revisits the same few candidates thousands of times)."""
        if getattr(self.model.config, "perform_fusion", False):
            sig = tuple(
                tuple(sorted((k, str(v)) for k, v in
                             strategy.for_op(op.name).axis_map.items()))
                for op in self.model.ops)
        else:
            sig = ()
        if sig in self._unit_cache:
            return self._unit_cache[sig]
        if sig == ():
            groups = [[op.name] for op in self.model.ops]
        else:
            from ..core.fusion import compute_fusion_groups
            groups = compute_fusion_groups(self.model, strategy)
        unit_of = {m: g[-1] for g in groups for m in g}
        unit_deps: Dict[str, List[str]] = {g[-1]: [] for g in groups}
        unit_consumers: Dict[str, List[str]] = {}
        for grp in groups:
            uid_ = grp[-1]
            seen = set()
            for m in grp:
                for t in self._ops_by_name[m].inputs:
                    p = self._producer.get(t.uid)
                    if p is None:
                        continue
                    pu = unit_of[p.name]
                    if pu != uid_ and pu not in seen:
                        seen.add(pu)
                        unit_deps[uid_].append(pu)
                        unit_consumers.setdefault(pu, []).append(uid_)
        self._unit_cache[sig] = (groups, unit_deps, unit_consumers)
        return self._unit_cache[sig]

    def simulate(self, strategy: Strategy,
                 dot_path: Optional[str] = None) -> float:
        """Estimated seconds per training step under `strategy`. The
        calibrated fixed dispatch cost (measure_step_overhead) is added
        once per step — strategy-independent, so it never changes the
        ranking, only absolute accuracy."""
        self.stats["full_sims"] += 1
        step_time, penalty = self._simulate_raw(strategy, dot_path)
        return step_time * self.time_scale + penalty + self.step_overhead

    def _staged_assignment(self, strategy: Strategy):
        """op->stage map when this strategy executes as a graph
        pipeline (mirrors model.compile's lowering decision: whole-op
        pins on non-embedding ops, or config.pipeline_stages), else
        None."""
        from ..parallel.graph_pipeline import (
            assignment_from_pins, balanced_stages, build_stage_plan,
            pick_pipe_axis)

        def viable(stage_of, vstages=1):
            if stage_of is None or max(stage_of.values()) < 1:
                return None
            n_stages = max(stage_of.values()) + 1
            # interleaved auto-cut: the pipe axis carries
            # n_stages / vstages devices (compile's lowering,
            # model.py pipeline_virtual_stages)
            if vstages > 1 and n_stages % vstages != 0:
                return None
            if pick_pipe_axis(self.mesh,
                              n_stages // max(1, vstages)) is None:
                return None  # compile would warn + replicate
            try:
                build_stage_plan(self.model, stage_of)
            except (ValueError, NotImplementedError):
                return None
            return stage_of

        stage_of = None
        # provenance for pricing: pins execute one stage per device
        # (v=1); the auto-cut path interleaves v stages per device.
        # _price_1f1b_ticks and staged_pipeline_cost must see the SAME
        # layout compile runs, not re-guess it from axis sizes.
        self._staged_vstages = 1
        try:
            stage_of = viable(assignment_from_pins(self.model, strategy))
        except (ValueError, NotImplementedError):
            stage_of = None  # compile warns and falls through, as here
        if stage_of is None \
                and getattr(self.model.config, "pipeline_stages", 0) > 1:
            # strategy-independent: the O(S*n^2) partition DP and plan
            # viability check run once, not per annealing candidate.
            # Mirror compile: auto-cut produces pipeline_stages * v
            # stages laid round-robin over pipeline_stages devices
            v = max(1, getattr(self.model.config,
                               "pipeline_virtual_stages", 1))
            S_req = self.model.config.pipeline_stages * v
            cache = self._balanced_cache
            # keyed by (S, v): the same stage count can be viable under
            # one interleaving factor and not another (the pipe axis
            # carries S/v devices), and the search sweeps v
            if (S_req, v) not in cache:
                cache[(S_req, v)] = viable(
                    balanced_stages(self.model, S_req), vstages=v)
            stage_of = cache[(S_req, v)]
            if stage_of is not None:
                self._staged_vstages = v
        return stage_of

    def _simulate_staged(self, strategy: Strategy, stage_of,
                         dot_path: Optional[str] = None,
                         record: bool = False):
        """Event-loop makespan of a graph-level staged strategy: one
        pipeline covering the whole model, per-stage tick costs from the
        cost model (staged_pipeline_cost), per-stage grad sync, memory
        from the schedule's activation peak."""
        from .cost_model import staged_pipeline_cost
        cfg = self.model.config
        vstages = max(1, getattr(self, "_staged_vstages", 1))
        n_stages = max(stage_of.values()) + 1
        key = (tuple(sorted(stage_of.items())),
               getattr(cfg, "pipeline_microbatches", 4),
               getattr(cfg, "pipeline_schedule", "gpipe"),
               vstages)
        cache = self._staged_cost_cache
        if key in cache:  # the annealing loop revisits candidates
            pc, syncs, mem = cache[key]
        else:
            pc, syncs, mem = cache[key] = staged_pipeline_cost(
                self.model, self.mesh, self.mm, stage_of, key[1],
                schedule=key[2],
                n_dev=(n_stages // vstages
                       if n_stages % vstages == 0 else None))
        tick_step = (self._price_1f1b_ticks(pc, syncs)
                     if key[2] == "1f1b" else None)
        if tick_step is not None and not dot_path and not record:
            return tick_step, self.mm.memory_penalty(mem)
        g = TaskGraph()
        exits: Dict[str, List] = {}
        fwd_join = self._expand_pipeline_fwd(g, "net", pc, [], exits)
        bwd_join = self._expand_pipeline_bwd(g, "net", pc, [fwd_join],
                                             exits["net"])
        for k, s in enumerate(syncs):
            if s > 0:
                g.add(f"net:sync.s{k}", s, "comm", [bwd_join])
        step_time = g.simulate(record)
        if record:
            self._last_graph = g
        if dot_path:
            g.export_dot(dot_path)
        if tick_step is not None:  # DOT exported; price stays tick-based
            step_time = tick_step
        return step_time, self.mm.memory_penalty(mem)

    def _price_1f1b_ticks(self, pc, syncs):
        """Price a 1F1B (incl. interleaved v > 1) staged strategy from
        the ACTUAL schedule tables the executor runs
        (parallel/graph_pipeline.interleaved_schedule). The executed
        program is a tick-lockstep lax.scan — every device runs one
        switch branch per tick, then both wire ppermutes — so tick t
        costs max over devices of the unit worked that tick, plus the
        two uniform-width wire hops; the bubble falls out of the IDLE
        entries. Returns None when the stage count does not divide the
        pipe axis (the executor would have rejected it too)."""
        import numpy as np
        S, M = pc.stages, pc.microbatches
        # _staged_assignment recorded which lowering produced this
        # stage_of (pins: one stage per device; auto-cut: v stages per
        # device) — price exactly that layout, never re-guess from axis
        # sizes (a same-size unrelated axis must not flip the schedule)
        v = max(1, getattr(self, "_staged_vstages", 1))
        if S % v != 0:
            return None
        n_dev = S // v
        kind, _mbi, sidx, _depth = _schedule_tables(n_dev, v, M)
        fwd = np.asarray([pc.fwd_at(k) for k in range(S)])
        bwd = np.asarray([pc.bwd_at(k) for k in range(S)])
        from ..parallel.graph_pipeline import BWD, FWD
        sidx_c = np.clip(sidx, 0, S - 1)
        cost = np.where(kind == FWD, fwd[sidx_c],
                        np.where(kind == BWD, bwd[sidx_c], 0.0))
        # two wires (activations +1 ring, cotangents -1 ring) ppermute
        # every tick at the max cut width (the wire pads to it)
        hop = 2.0 * (max(pc.hops) if pc.hops else pc.hop)
        ticks = float(cost.max(axis=1).sum()) + kind.shape[0] * hop
        return ticks + sum(syncs)

    def _simulate_raw(self, strategy: Strategy,
                      dot_path: Optional[str] = None,
                      record: bool = False):
        """Returns (unscaled step seconds, memory penalty seconds)."""
        stage_of = self._staged_assignment(strategy)
        if stage_of is not None:
            return self._simulate_staged(strategy, stage_of, dot_path,
                                         record)
        built = self._build_graph(strategy)
        step_time = built.graph.simulate(record)
        if record:
            self._last_graph = built.graph
        if dot_path:
            built.graph.export_dot(dot_path)
        return step_time, self.mm.memory_penalty(built.total_mem)

    def export_schedule(self, strategy: Strategy, path: str) -> dict:
        """Export the simulated event-loop schedule of `strategy` as a
        Perfetto-loadable Chrome trace (rendered through
        utils/telemetry.Telemetry.export_chrome_trace): one track per
        simulated resource (compute stream, ICI fabric, per-device /
        per-stage rows), each task a complete span carrying its exact
        start/end seconds and critical-path flag in ``args``, plus
        anchor spans for the calibrated dispatch overhead and the HBM
        penalty so the trace's exact end time
        (``metadata["makespan_s"]``, = the max ``t_end_s`` over events)
        equals :meth:`simulate`'s return for the same strategy
        bit-exactly. Returns a summary dict (path, makespan_s, task and
        critical-path counts)."""
        from ..utils.telemetry import Telemetry
        self._last_graph = None
        step_raw, penalty = self._simulate_raw(strategy, record=True)
        g = self._last_graph
        # the SAME float expression simulate() evaluates — bit-equality
        # of the trace end with the priced step time is the contract
        total = step_raw * self.time_scale + penalty + self.step_overhead
        crit = g.critical_path()
        scale = self.time_scale
        off = self.step_overhead
        # a tick-priced 1F1B staged strategy returns the tick-table
        # price while the recorded graph is the event-loop VISUAL —
        # normalize the graph onto the priced span (factor is exactly
        # 1.0 whenever the event loop IS the price, i.e. every
        # non-staged and gpipe-staged strategy) and clamp to the
        # anchor so the trace end stays bit-equal to simulate()
        graph_end = max((t.finish_time for t in g.tasks), default=0.0)
        eff = scale if graph_end == step_raw or graph_end <= 0.0 \
            else scale * (step_raw / graph_end)
        pen_start = off + step_raw * scale
        events = [t for t in g.tasks if t.duration > 0.0]
        # t0=0.0 pins the trace clock: spans carry trace-absolute
        # simulator seconds, not wall time
        tel = Telemetry(enabled=True, max_events=len(events) + 8,
                        t0=0.0)
        if off > 0.0:
            tel.span(("sim", "host"), "step_overhead", 0.0, off,
                     args={"t_start_s": 0.0, "t_end_s": off,
                           "crit": False})
        n_crit = 0
        for t in events:
            t0 = min(off + t.start_time * eff, pen_start)
            t1 = min(off + t.finish_time * eff, pen_start)
            on_crit = id(t) in crit
            n_crit += bool(on_crit)
            tel.span(_res_track(t.resource), t.name, t0, t1,
                     args={"t_start_s": t0, "t_end_s": t1,
                           "crit": bool(on_crit),
                           "res": _res_label(t.resource)})
        # tail anchor: the (strategy-dependent) HBM penalty closes the
        # trace at the exact priced step time, zero-width when no
        # penalty applies
        tel.span(("sim", "hbm"), "hbm_penalty", pen_start, total,
                 args={"t_start_s": pen_start, "t_end_s": total,
                       "crit": False, "penalty_s": penalty})
        summary = {
            "path": path, "makespan_s": total,
            "event_loop_s": step_raw, "time_scale": scale,
            "hbm_penalty_s": penalty, "step_overhead_s": off,
            "tasks": len(events), "critical_tasks": n_crit,
            "domain": "train",
        }
        tel.export_chrome_trace(path, metadata=dict(summary))
        return summary

    # task classes of the drift attribution (docs/observability.md):
    # the train half — compute fwd/bwd, the optimizer-update sweep,
    # fwd/bwd collectives, and the DP grad sync (bucketed or per-op)
    TRAIN_TASK_CLASSES = ("fwd", "bwd", "update", "collective",
                          "grad_sync", "overhead")

    def step_breakdown(self, strategy: Strategy) -> Dict[str, float]:
        """Predicted seconds per task CLASS for one step of `strategy`
        — the attribution vector the drift calibrator aligns measured
        steps against (utils/telemetry.record_drift(breakdown=...)).
        These are summed task durations (scaled like simulate()), not
        makespan shares: overlapped classes intentionally sum past the
        critical path, which is exactly what lets the least-squares
        attribution tell WHICH term mis-prices."""
        out = {k: 0.0 for k in self.TRAIN_TASK_CLASSES}
        for op in self.model.ops:
            c = self._op_cost(op, strategy)
            out["fwd"] += c.fwd
            out["bwd"] += c.bwd
            out["update"] += c.update
            out["collective"] += c.fwd_comm + c.bwd_comm
            out["grad_sync"] += c.sync
        s = self.time_scale
        out = {k: v * s for k, v in out.items()}
        out["overhead"] = self.step_overhead
        return out

    def _build_graph(self, strategy: Strategy) -> "_BuiltGraph":
        """Build the (non-staged) task graph for `strategy`. Comm and
        grad-sync tasks are ALWAYS materialized, zero-duration when the
        cost is zero — numerically identical to skipping them (the
        zero-duration pass-through in TaskGraph.simulate), but it keeps
        the task-graph STRUCTURE independent of the axis maps, which is
        what lets simulate_delta reuse one scheduled template across
        rewrite/propagate moves and only re-cost the changed ops."""
        g = TaskGraph()
        fwd_tasks: Dict[str, SimTask] = {}

        total_mem = 0.0
        costs = {op.name: self._op_cost(op, strategy)
                 for op in self.model.ops}

        # fusion (reference FusedOp simulated as ONE task per group,
        # fused.cu fwd/bwd dispatch): each unit is a singleton op or a
        # same-strategy chain costed as one task; member costs (incl.
        # intrinsic collectives like TP all-reduces) are summed.
        groups, unit_deps, unit_consumers = self._units_for(strategy)
        unit_cost: Dict[str, OpCost] = {}
        for grp in groups:
            c = costs[grp[0]]
            for m in grp[1:]:
                c = c.merge(costs[m])
            unit_cost[grp[-1]] = c
        unit_order = [g_[-1] for g_ in groups]

        # compute-resource assignment: mesh-uniform SPMD units share one
        # "compute" stream; a device-placed unit (OpStrategy.device_ids)
        # occupies only its own devices, so disjoint placements run
        # concurrently (reference: ops with disjoint ParallelConfig
        # device_ids proceed in parallel under Legion's dataflow).
        singleton = {grp[-1] for grp in groups if len(grp) == 1}
        placed = {u: strategy.for_op(u).device_ids for u in unit_order
                  if u in singleton and strategy.for_op(u).device_ids}
        all_devs = [("dev", i) for i in range(int(self.mesh.size))] \
            if placed else []

        def res_for(u):
            if u in placed:
                return [("dev", int(i)) for i in placed[u]]
            return ["compute"] + all_devs if placed else "compute"

        # pipeline units (singleton pipeline_blocks with layer->pipe):
        # expanded into the real (microbatch, stage) GPipe schedule over
        # per-stage resources instead of one closed-form task (the event
        # loop the reference runs for every task, simulator.cc:330-629).
        expanded = {u for u in unit_order
                    if unit_cost[u].pipeline is not None and u in singleton}
        pipe_fwd_exit: Dict[str, List[List[SimTask]]] = {}
        slots: Dict[str, Dict[str, SimTask]] = {}

        # forward chain
        for u in unit_order:
            c = unit_cost[u]
            deps = [fwd_tasks[pu] for pu in unit_deps[u] if pu in fwd_tasks]
            if u in expanded:
                fwd_tasks[u] = self._expand_pipeline_fwd(
                    g, u, c.pipeline, deps, pipe_fwd_exit)
                total_mem += c.mem
                continue
            comm = g.add(f"{u}:fwd_comm", c.fwd_comm, "comm", deps)
            deps = deps + [comm]
            fwd_tasks[u] = g.add(f"{u}:fwd", c.fwd, res_for(u), deps)
            slots[u] = {"fwd_comm": comm, "fwd": fwd_tasks[u]}
            total_mem += c.mem

        # bucketed grad sync (FFConfig.grad_bucket_mb, core/overlap.py):
        # when the runtime buckets, the simulator prices the SAME
        # partition — per-op sync tasks go zero-duration (keeping the
        # 5-slot structure the delta template splices into) and one
        # bucket task per bucket carries the combined all-reduce of its
        # members' payloads, depending on the members' backward tasks.
        # The partition walks UNITS (singleton ops when fusion is off —
        # then it equals core/overlap.grad_buckets exactly, the
        # executor's partition) accumulating the dense master bytes of
        # each unit's member ops; sparse-update tables stay outside
        # (their row grads scatter, keeping their own sync task), as do
        # pipeline-expanded and device-placed units. A serialized
        # (--no-overlap-sync) search keeps the legacy per-op syncs.
        bucket_members: List[List[str]] = []
        bucket_set: set = set()
        if self.overlap and self.bucket_mb > 0:
            from ..core.overlap import eligible_sparse_ops
            sparse = eligible_sparse_ops(self.model)
            members_of = {grp[-1]: grp for grp in groups}
            limit = float(self.bucket_mb) * (1 << 20)
            cur: List[str] = []
            cur_bytes = 0.0
            for u in unit_order:
                if u in expanded or u in placed:
                    continue
                w = sum(float(self._ops_by_name[m].weight_bytes())
                        for m in members_of[u]
                        if m not in sparse
                        and self._ops_by_name[m].weight_specs())
                if w <= 0:
                    continue
                cur.append(u)
                cur_bytes += w
                if cur_bytes >= limit:
                    bucket_members.append(cur)
                    cur, cur_bytes = [], 0.0
            if cur:
                bucket_members.append(cur)
            bucket_set = {n for m in bucket_members for n in m}

        # backward chain (reverse graph)
        bwd_tasks: Dict[str, SimTask] = {}
        sync_tasks: List[SimTask] = []
        for u in reversed(unit_order):
            c = unit_cost[u]
            deps = [bwd_tasks[cons] for cons in unit_consumers.get(u, [])
                    if cons in bwd_tasks]
            if not deps:
                deps = [fwd_tasks[unit_order[-1]]]
            if u in expanded:
                bwd_tasks[u] = self._expand_pipeline_bwd(
                    g, u, c.pipeline, deps, pipe_fwd_exit[u])
            else:
                comm = g.add(f"{u}:bwd_comm", c.bwd_comm, "comm", deps)
                deps = deps + [comm]
                bwd_tasks[u] = g.add(f"{u}:bwd", c.bwd + c.update,
                                     res_for(u), deps)
                slots[u]["bwd_comm"] = comm
                slots[u]["bwd"] = bwd_tasks[u]
            # grad all-reduce may overlap the rest of backward
            # (reference overlap flag, simulator.cc:393-497); bucketed
            # members sync through their bucket task instead
            st = g.add(f"{u}:grad_sync",
                       0.0 if u in bucket_set else c.sync,
                       "comm", [bwd_tasks[u]])
            sync_tasks.append(st)
            if u in slots:
                slots[u]["sync"] = st

        bucket_tasks: List[SimTask] = []
        for k, members in enumerate(bucket_members):
            payload = 0.0
            for m in members:   # walk order — the delta path re-sums
                # UNIT cost, not costs[m]: the zeroed per-unit sync
                # task covered the whole fused group's payload, so the
                # bucket must carry the merged sum (identical to the
                # per-op cost when fusion is off — the delta path,
                # fusion-disabled, re-sums the same values bit-equally)
                payload += unit_cost[m].sync_bytes
            bucket_tasks.append(g.add(
                f"grad_bucket_sync.{k}", self._bucket_sync_cost(payload),
                "comm", [bwd_tasks[m] for m in members]))

        if not self.overlap and sync_tasks:
            # serialize syncs after all backward work: model by chaining
            last_bwd = bwd_tasks[unit_order[0]]
            for st in sync_tasks:
                st.deps.append(last_bwd)

        return _BuiltGraph(graph=g, total_mem=total_mem, costs=costs,
                           slots=slots, expanded=expanded, placed=placed,
                           bucket_members=bucket_members,
                           bucket_tasks=bucket_tasks)

    def _bucket_sync_cost(self, payload_bytes: float) -> float:
        """One bucket's combined DP all-reduce: the summed per-device
        payload over the mesh's data axis — one latency term per
        BUCKET, which is exactly what bucketing buys over per-op
        syncs."""
        dp = int(self.mesh.shape.get("data", 1))
        if dp <= 1 or payload_bytes <= 0:
            return 0.0
        return self.mm.all_reduce(
            payload_bytes, dp, "data" if "data" in self.mesh.shape
            else None)

    # ---------------- delta simulation ----------------
    def delta_rebase(self, strategy: Strategy) -> bool:
        """(Re)build the delta template from `strategy` — the scheduled
        task graph subsequent simulate_delta calls splice into. Returns
        False (template cleared) when the delta path cannot represent
        this strategy: fused searches (unit partition moves with the
        axis maps), staged/pinned pipelines, or device-placed ops
        (per-device resource lists change with the assignment)."""
        self._delta = None
        cfg = getattr(self.model, "config", None)
        if not getattr(cfg, "search_delta_sim", True):
            return False
        if getattr(cfg, "perform_fusion", False):
            return False
        # cheap pre-checks before paying for a graph build: placed ops
        # get per-device resource lists (structure tracks the
        # assignment), and _anneal_chain re-rebases after every
        # accepted structural move — a placed-heavy walk would
        # otherwise pay a wasted full build per accepted move
        if any(strategy.for_op(op.name).device_ids
               for op in self.model.ops):
            return False
        if self._staged_assignment(strategy) is not None:
            return False
        built = self._build_graph(strategy)
        if built.placed:  # unreachable given the pre-check; defensive
            return False
        tasks = built.graph.tasks
        index = {id(task): i for i, task in enumerate(tasks)}
        n = len(tasks)
        t = _DeltaTemplate()
        t.durations = [task.duration for task in tasks]
        t.ndeps0 = [len(task.deps) for task in tasks]
        children: List[List[int]] = [[] for _ in range(n)]
        for i, task in enumerate(tasks):
            for d in task.deps:
                children[index[id(d)]].append(i)
        t.children = [tuple(c) for c in children]
        t.roots = tuple(i for i, task in enumerate(tasks)
                        if not task.deps)
        res_ids: Dict[object, int] = {}
        res = []
        for task in tasks:
            key = (tuple(task.resource)
                   if isinstance(task.resource, list) else task.resource)
            if key not in res_ids:
                res_ids[key] = len(res_ids)
            res.append(res_ids[key])
        t.res = res
        t.n_res = len(res_ids)
        t.op_slots = {u: tuple(index[id(d[sn])] for sn in _SLOT_NAMES)
                      for u, d in built.slots.items()}
        t.op_sig = {op.name: _axis_sig(strategy.for_op(op.name))
                    for op in self.model.ops}
        t.op_class = {name: built.costs[name].pipeline is not None
                      for name in t.op_sig}
        t.op_mem = {name: built.costs[name].mem for name in t.op_sig}
        t.op_order = tuple(op.name for op in self.model.ops)
        # bucketed grad sync: per-op payloads + bucket membership so a
        # moved op's bucket re-prices from the SAME member sum the full
        # build uses (bit-equal), spliced into the bucket task's slot
        t.op_sync_bytes = {name: built.costs[name].sync_bytes
                           for name in t.op_sig}
        t.bucket_members = [tuple(m) for m in built.bucket_members]
        t.bucket_of = {name: k for k, m in enumerate(t.bucket_members)
                       for name in m}
        t.bucket_slot = [index[id(task)] for task in built.bucket_tasks]
        self._delta = t
        return True

    def simulate_delta(self, strategy: Strategy,
                       changed_ops) -> Optional[_DeltaToken]:
        """Delta re-simulation of `strategy`, which must differ from the
        template's base only in `changed_ops`: re-cost just those ops
        (cache-served for revisited candidates), splice the durations
        into the cached scheduled graph, and replay the event loop over
        the flat arrays. Returns None when the move changes task-graph
        STRUCTURE (op enters/leaves pipeline expansion or device
        placement) — the caller falls back to a full simulate() and
        delta_rebase(). The returned token's mutations are already
        applied; call delta_reject(token) to roll them back when the
        move is rejected (accepting needs no call)."""
        t = self._delta
        if t is None:
            return None
        updates = []
        for name in changed_ops:
            op = self._ops_by_name.get(name)
            if op is None:
                continue
            s = strategy.for_op(name)
            sig = _axis_sig(s)
            if sig == t.op_sig.get(name):
                continue  # no-op move (picked the current candidate)
            if name not in t.op_slots or s.device_ids:
                # pipeline-expanded unit or a device-placement rewrite:
                # the template's task structure no longer matches
                self.stats["delta_fallbacks"] += 1
                return None
            c = self._op_cost_for(op, s, sig)
            if (c.pipeline is not None) != t.op_class[name]:
                self.stats["delta_fallbacks"] += 1
                return None
            updates.append((name, sig, c))
        undo = []
        d = t.durations
        touched_buckets = set()
        for name, sig, c in updates:
            i_fc, i_f, i_bc, i_b, i_s = t.op_slots[name]
            undo.append((name, t.op_sig[name], t.op_mem[name],
                         t.op_sync_bytes[name],
                         (d[i_fc], d[i_f], d[i_bc], d[i_b], d[i_s])))
            d[i_fc] = c.fwd_comm
            d[i_f] = c.fwd
            d[i_bc] = c.bwd_comm
            d[i_b] = c.bwd + c.update
            b = t.bucket_of.get(name)
            # bucketed members keep their zero per-op sync slot; their
            # bucket's task re-prices below from the new payloads
            d[i_s] = 0.0 if b is not None else c.sync
            if b is not None:
                touched_buckets.add(b)
            t.op_sig[name] = sig
            t.op_mem[name] = c.mem
            t.op_sync_bytes[name] = c.sync_bytes
        bucket_undo = []
        for b in sorted(touched_buckets):
            i_bk = t.bucket_slot[b]
            bucket_undo.append((i_bk, d[i_bk]))
            payload = 0.0
            for m in t.bucket_members[b]:   # same walk-order sum as
                payload += t.op_sync_bytes[m]  # _build_graph: bit-equal
            d[i_bk] = self._bucket_sync_cost(payload)
        makespan = self._replay(t)
        total_mem = 0.0
        om = t.op_mem
        for name in t.op_order:  # same accumulation order as
            total_mem += om[name]  # _build_graph -> bit-equal penalty
        self.stats["delta_sims"] += 1
        return _DeltaToken(
            cost=(makespan * self.time_scale
                  + self.mm.memory_penalty(total_mem)
                  + self.step_overhead),
            undo=(undo, bucket_undo))

    def delta_reject(self, tok: _DeltaToken) -> None:
        """Roll the template back to its pre-simulate_delta state."""
        t = self._delta
        if t is None:
            return
        d = t.durations
        ops_undo, bucket_undo = tok.undo
        for name, sig, mem, sync_bytes, durs in ops_undo:
            i_fc, i_f, i_bc, i_b, i_s = t.op_slots[name]
            d[i_fc], d[i_f], d[i_bc], d[i_b], d[i_s] = durs
            t.op_sig[name] = sig
            t.op_mem[name] = mem
            t.op_sync_bytes[name] = sync_bytes
        for i_bk, dur in bucket_undo:
            d[i_bk] = dur

    def _replay(self, t: _DeltaTemplate) -> float:
        """Array-form of TaskGraph.simulate over the cached template:
        identical pop order (ready-time heap, creation-order counter
        tie-break) and identical zero-duration transparency, so the
        returned makespan is bit-equal to a full rebuild-and-simulate
        of the same strategy — without allocating a single SimTask."""
        heappush = heapq.heappush
        heappop = heapq.heappop
        durations = t.durations
        children = t.children
        res = t.res
        ndeps = t.ndeps0[:]
        ready = [0.0] * len(durations)
        free = [0.0] * t.n_res
        q = [(0.0, i, idx) for i, idx in enumerate(t.roots)]
        counter = len(q)
        makespan = 0.0
        while q:
            r, _, i = heappop(q)
            dur = durations[i]
            if dur == 0.0:
                f = r
            else:
                k = res[i]
                fr = free[k]
                f = (fr if fr > r else r) + dur
                free[k] = f
                if f > makespan:
                    makespan = f
            for ch in children[i]:
                if f > ready[ch]:
                    ready[ch] = f
                ndeps[ch] -= 1
                if ndeps[ch] == 0:
                    heappush(q, (ready[ch], counter, ch))
                    counter += 1
        return makespan

    def _expand_pipeline_fwd(self, g, u, pc, ext_deps, pipe_fwd_exit):
        """Emit the GPipe forward: microbatch m flows stage 0..S-1, one
        hop between stages; stage k is its own resource, so the bubble
        emerges from the event loop rather than a closed form. Returns a
        zero-duration join task (= the unit's fwd handle)."""
        S, M = pc.stages, pc.microbatches
        rows: List[List[SimTask]] = []
        for m in range(M):
            row = []
            prev = None
            for k in range(S):
                deps = list(ext_deps) if k == 0 else []
                if prev is not None:
                    hop = pc.hop_at(k)
                    if hop > 0:
                        h = g.add(f"{u}:f{m}.hop{k}", hop, "comm",
                                  [prev])
                        deps.append(h)
                    else:
                        deps.append(prev)
                prev = g.add(f"{u}:f{m}.s{k}", pc.fwd_at(k),
                             ("stage", u, k), deps)
                row.append(prev)
            rows.append(row)
        pipe_fwd_exit[u] = rows
        join = g.add(f"{u}:fwd_join", 0.0, ("join", u, "f"),
                     [r[-1] for r in rows])
        return join

    def _expand_pipeline_bwd(self, g, u, pc, ext_deps, fwd_rows):
        """GPipe backward: microbatch m flows stage S-1..0 (each bwd tick
        also depends on that microbatch's forward at the same stage —
        stashed activations)."""
        S, M = pc.stages, pc.microbatches
        exits = []
        for m in range(M):
            prev = None
            for k in reversed(range(S)):
                deps = list(ext_deps) if k == S - 1 else []
                deps.append(fwd_rows[m][k])
                if prev is not None:
                    hop = pc.hop_at(k + 1)
                    if hop > 0:
                        h = g.add(f"{u}:b{m}.hop{k}", hop, "comm",
                                  [prev])
                        deps.append(h)
                    else:
                        deps.append(prev)
                prev = g.add(f"{u}:b{m}.s{k}", pc.bwd_at(k),
                             ("stage", u, k), deps)
            exits.append(prev)
        return g.add(f"{u}:bwd_join", 0.0, ("join", u, "b"), exits)

    def memory_per_device(self, strategy: Strategy) -> float:
        return sum(self._op_cost(op, strategy).mem for op in self.model.ops)


# ---------------------------------------------------------------------------
# Serve-step simulation (tensor-parallel sharded serving, PR 9)
# ---------------------------------------------------------------------------

def serve_task_schedule(tasks) -> Dict[str, tuple]:
    """(start, finish) seconds per task of a serve-step task graph
    (cost_model.serve_step_tasks): finish(t) = duration(t) +
    max(finish(deps)). The ONE chain evaluation — the makespan
    (simulate_serve_tasks) and the schedule export derive from this
    same float accumulation, which is what keeps the exported trace's
    end time bit-equal to the simulated step."""
    sched: Dict[str, tuple] = {}
    for t in tasks:  # serve_step_tasks emits in dependency order
        start = max((sched[d][1] for d in t.deps if d in sched),
                    default=0.0)
        sched[t.name] = (start, start + t.seconds)
    return sched


def simulate_serve_tasks(tasks) -> float:
    """Makespan of a serve-step task graph (cost_model.serve_step_tasks)
    — the critical path over named dependencies. Tensor-parallel
    serving's collectives sit ON the critical path (each all-reduce
    feeds the very next matmul — there is no second microbatch to hide
    them behind, unlike training's bucketed grad sync), so the chain
    evaluation IS the event loop (serve_task_schedule). Kept
    structural (not a plain sum) so a future serve graph with parallel
    branches (e.g. draft-LM lanes priced beside the target) simulates
    unchanged."""
    return max((f for _, f in serve_task_schedule(tasks).values()),
               default=0.0)


def simulate_serve_step(arch, tensor_parallel: int,
                        mm: Optional[TPUMachineModel] = None, *,
                        lanes: Optional[int] = None,
                        axis_dims: tuple = (),
                        transfer_tokens: int = 0) -> float:
    """Simulated seconds of ONE mixed serving step with `lanes` query
    lanes (default: a full decode step — `arch.decode_lanes`) at the
    given tensor-parallel degree, including the reference-style
    1ms/MB penalty when the per-device resident bytes exceed HBM
    (simulator.cc:603-628 — what makes a too-big-for-one-chip model
    price its own sharding). `axis_dims` pins the serve axis onto
    physical torus dims (machine_model._phys) — the axis-assignment
    half of the placement search. `transfer_tokens` > 0 prices a
    disaggregated page handoff of that many tokens riding the host
    link BESIDE the step (cost_model.serve_step_tasks): the makespan
    grows only when the link is the bottleneck — the decode-engine
    import-while-decoding steady state."""
    from .cost_model import (SERVE_AXIS, serve_device_bytes,
                             serve_step_tasks)
    if mm is None:
        mm = default_machine_model()
    if axis_dims:
        mm = dataclasses.replace(
            mm, axis_topology={**mm.axis_topology,
                               SERVE_AXIS: tuple(axis_dims)})
    step = simulate_serve_tasks(serve_step_tasks(
        arch, tensor_parallel, mm,
        lanes=int(arch.decode_lanes if lanes is None else lanes),
        transfer_tokens=int(transfer_tokens)))
    return step + mm.memory_penalty(
        serve_device_bytes(arch, tensor_parallel))


# task classes of the serve drift attribution: the paged-attention
# kernel, the dense matmuls (qkv/wo/ffn/head/embed), the tensor-
# parallel collectives (all-reduces + the logits all-gather), and the
# disaggregated page-handoff host-link transfer
SERVE_TASK_CLASSES = ("attention", "matmul", "collective", "transfer")


def serve_task_class(task) -> str:
    """Attribution class of one ServeTask (cost_model.serve_step_tasks
    names are stable: ``l{i}.attn`` is the paged-attention kernel)."""
    if task.kind == "collective":
        return "collective"
    if task.kind == "transfer":
        return "transfer"
    if task.name.endswith(".attn"):
        return "attention"
    return "matmul"


def serve_step_breakdown(arch, tensor_parallel: int,
                         mm: Optional[TPUMachineModel] = None, *,
                         lanes: Optional[int] = None,
                         axis_dims: tuple = (),
                         transfer_tokens: int = 0) -> Dict[str, float]:
    """Predicted seconds per task class of ONE mixed serving step —
    the serve half of the drift attribution vector. The serve compute
    graph is a serial chain, so with no transfer task the classes
    (plus the HBM penalty) sum exactly to
    :func:`simulate_serve_step`; a priced handoff runs BESIDE the
    chain, so its class reports its own seconds while the makespan
    stays max(chain, transfer)."""
    from .cost_model import SERVE_AXIS, serve_device_bytes, \
        serve_step_tasks
    if mm is None:
        mm = default_machine_model()
    if axis_dims:
        mm = dataclasses.replace(
            mm, axis_topology={**mm.axis_topology,
                               SERVE_AXIS: tuple(axis_dims)})
    out = {k: 0.0 for k in SERVE_TASK_CLASSES}
    for t in serve_step_tasks(
            arch, tensor_parallel, mm,
            lanes=int(arch.decode_lanes if lanes is None else lanes),
            transfer_tokens=int(transfer_tokens)):
        out[serve_task_class(t)] += t.seconds
    out["hbm_penalty"] = mm.memory_penalty(
        serve_device_bytes(arch, tensor_parallel))
    return out


def export_serve_schedule(arch, tensor_parallel: int, path: str,
                          mm: Optional[TPUMachineModel] = None, *,
                          lanes: Optional[int] = None,
                          axis_dims: tuple = (),
                          transfer_tokens: int = 0) -> dict:
    """Perfetto-loadable export of the simulated serve-step schedule
    (the serving mirror of Simulator.export_schedule): one track per
    task class, every task a complete span with exact start/end seconds
    in ``args``, an ``hbm_penalty`` anchor closing the trace at exactly
    :func:`simulate_serve_step`'s return for the same placement
    (``metadata["makespan_s"]``). The serve chain is serial, so every
    task is on the critical path by construction."""
    from ..utils.telemetry import Telemetry
    from .cost_model import SERVE_AXIS, serve_device_bytes, \
        serve_step_tasks
    if mm is None:
        mm = default_machine_model()
    if axis_dims:
        mm = dataclasses.replace(
            mm, axis_topology={**mm.axis_topology,
                               SERVE_AXIS: tuple(axis_dims)})
    tasks = serve_step_tasks(
        arch, tensor_parallel, mm,
        lanes=int(arch.decode_lanes if lanes is None else lanes),
        transfer_tokens=int(transfer_tokens))
    penalty = mm.memory_penalty(
        serve_device_bytes(arch, tensor_parallel))
    # the SAME chain evaluation simulate_serve_tasks prices from
    sched = serve_task_schedule(tasks)
    tel = Telemetry(enabled=True, max_events=len(tasks) + 8, t0=0.0)
    end = 0.0
    for t in tasks:
        start, finish = sched[t.name]
        end = max(end, finish)
        if t.seconds > 0.0:
            tel.span(("sim", serve_task_class(t)), t.name, start,
                     finish,
                     args={"t_start_s": start,
                           "t_end_s": finish, "crit": True,
                           "kind": t.kind})
    total = end + penalty  # simulate_serve_step's float expression
    tel.span(("sim", "hbm"), "hbm_penalty", end, total,
             args={"t_start_s": end, "t_end_s": total, "crit": False,
                   "penalty_s": penalty})
    summary = {
        "path": path, "makespan_s": total, "event_loop_s": end,
        "hbm_penalty_s": penalty, "tasks": len(tasks),
        "tensor_parallel": int(tensor_parallel), "domain": "serve",
    }
    tel.export_chrome_trace(path, metadata=dict(summary))
    return summary
