"""On-device microbenchmarks to calibrate the cost model.

The analog of the reference's `inner_measure_operator_cost`
(src/runtime/model.cu:20-62): run real kernels (warmup + repeats) and
record achieved efficiency. On TPU we calibrate the machine model's
efficiency factors once (matmul MXU fraction, elementwise HBM fraction)
instead of timing every (op, config) pair — candidate strategies can't be
individually timed without a recompile each (SURVEY.md 7 hard part (d)).

NOTE on timing: through remote-tunnel platforms block_until_ready may not
synchronize; a device->host scalar fetch is used to delimit timing.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .machine_model import TPUMachineModel


def _sync(x) -> float:
    import jax.numpy as jnp
    return float(jnp.ravel(x)[0])


def measure_matmul_efficiency(mm: TPUMachineModel, n: int = 8192,
                              repeats: int = 30) -> float:
    # repeats must be large enough that total device time >> one
    # host<->device round trip (remote tunnels add ~100ms per sync)
    import jax
    import jax.numpy as jnp
    x = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def f(a):
        return jnp.dot(a, a, preferred_element_type=jnp.float32).astype(
            jnp.bfloat16)

    y = f(x)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(repeats):
        y = f(y)
    _sync(y)
    dt = (time.perf_counter() - t0) / repeats
    achieved = 2.0 * n ** 3 / dt
    return min(1.0, achieved / mm.spec.peak_flops)


def measure_elementwise_efficiency(mm: TPUMachineModel, n: int = 16384,
                                   repeats: int = 100) -> float:
    import jax
    import jax.numpy as jnp
    x = jnp.ones((n, n), jnp.float32)

    @jax.jit
    def f(a):
        return a * 1.0001 + 0.5

    y = f(x)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(repeats):
        y = f(y)
    _sync(y)
    dt = (time.perf_counter() - t0) / repeats
    achieved_bytes = 2.0 * x.size * 4 / dt  # read + write
    return min(1.0, achieved_bytes / mm.spec.hbm_bandwidth)


def calibrate(mm: TPUMachineModel, save_path: Optional[str] = None
              ) -> TPUMachineModel:
    """Update mm.efficiency from real kernel timings on this device."""
    try:
        mm.efficiency["matmul"] = max(0.05, measure_matmul_efficiency(mm))
        mm.efficiency["elementwise"] = max(
            0.05, measure_elementwise_efficiency(mm))
    except Exception as e:  # CPU or restricted platform: keep defaults
        import warnings
        warnings.warn(f"calibration failed, using defaults: {e}")
    if save_path:
        mm.save_calibration(save_path)
    return mm
