"""On-device microbenchmarks to calibrate the cost model.

The analog of the reference's `inner_measure_operator_cost`
(src/runtime/model.cu:20-62): run real kernels (warmup + repeats) and
record achieved efficiency. On TPU we calibrate the machine model's
efficiency factors once (matmul MXU fraction, elementwise HBM fraction)
instead of timing every (op, config) pair — candidate strategies can't be
individually timed without a recompile each (SURVEY.md 7 hard part (d)).

NOTE on timing: through remote-tunnel platforms block_until_ready may not
synchronize; a device->host scalar fetch is used to delimit timing.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from .machine_model import TPUMachineModel, default_machine_model


def _sync(x) -> float:
    import jax.numpy as jnp
    return float(jnp.ravel(x)[0])


def measure_matmul_efficiency(mm: TPUMachineModel, n: int = 8192,
                              repeats: int = 30, dtype=None) -> float:
    # repeats must be large enough that total device time >> one
    # host<->device round trip (remote tunnels add ~100ms per sync)
    import jax
    import jax.numpy as jnp
    dtype = jnp.dtype(dtype if dtype is not None else jnp.bfloat16)
    x = jnp.ones((n, n), dtype)

    @jax.jit
    def f(a):
        return jnp.dot(a, a, preferred_element_type=jnp.float32).astype(
            dtype)

    y = f(x)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(repeats):
        y = f(y)
    _sync(y)
    dt = (time.perf_counter() - t0) / repeats
    achieved = 2.0 * n ** 3 / dt
    # achieved fraction of THAT dtype's peak (peak_flops_for), so the
    # factor composes with the per-dtype rate instead of double-
    # counting it (machine_model.compute_time)
    return min(1.0, achieved / mm.peak_flops_for(dtype.name))


def measure_conv_efficiency(mm: TPUMachineModel, repeats: int = 20
                            ) -> float:
    """Achieved MXU fraction for convolution — measured separately from
    big GEMM because im2col/layout overheads put convs well below the
    dense-matmul roofline, and ranking conv strategies by the GEMM
    factor is a guess (VERDICT r2 #3; reference conv_2d.cu:173-260
    auto-selects per-shape algorithms by measurement). Two Inception-
    representative shapes (3x3 s1 mid-size, 1x1 channel-mixing),
    NHWC/bf16 — the bench compute layout; returns the FLOP-weighted
    achieved fraction."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    shapes = [
        # (batch, h, w, cin, cout, k)
        (64, 56, 56, 64, 128, 3),
        (64, 28, 28, 256, 256, 1),
    ]
    dn = jax.lax.conv_dimension_numbers(
        (1, 1, 1, 1), (1, 1, 1, 1), ("NHWC", "HWIO", "NHWC"))
    total_flops = 0.0
    total_time = 0.0
    for (b, h, w, cin, cout, k) in shapes:
        x = jnp.ones((b, h, w, cin), jnp.bfloat16)
        kern = jnp.ones((k, k, cin, cout), jnp.bfloat16)

        @partial(jax.jit)
        def f(a, kr):
            return jax.lax.conv_general_dilated(
                a, kr, (1, 1), "SAME", dimension_numbers=dn,
                preferred_element_type=jnp.float32).astype(jnp.bfloat16)

        y = f(x, kern)
        _sync(y)
        t0 = time.perf_counter()
        for _ in range(repeats):
            y = f(x, kern)
        _sync(y)
        total_time += (time.perf_counter() - t0) / repeats
        total_flops += 2.0 * b * h * w * cout * cin * k * k
    # back-to-back effective rate over the shape mix
    achieved = total_flops / total_time
    return min(1.0, achieved / mm.spec.peak_flops)


def measure_elementwise_efficiency(mm: TPUMachineModel, n: int = 16384,
                                   repeats: int = 100) -> float:
    import jax
    import jax.numpy as jnp
    x = jnp.ones((n, n), jnp.float32)

    @jax.jit
    def f(a):
        return a * 1.0001 + 0.5

    y = f(x)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(repeats):
        y = f(y)
    _sync(y)
    dt = (time.perf_counter() - t0) / repeats
    achieved_bytes = 2.0 * x.size * 4 / dt  # read + write
    return min(1.0, achieved_bytes / mm.spec.hbm_bandwidth)


def measure_step_overhead(repeats: int = 50) -> float:
    """Fixed per-dispatch cost of one queued train step (host dispatch +
    tunnel pipelining). Measured by timing a trivial jitted op with the
    queue kept full — the regime fit()/bench use. The reference's analog
    is Legion's per-task runtime overhead, amortized there by tracing."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def tiny(a):
        return a * 1.0001 + 1.0

    x = jnp.ones((8, 8), jnp.float32)
    y = tiny(x)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(repeats):
        y = tiny(y)
    _sync(y)
    return (time.perf_counter() - t0) / repeats


def calibrate(mm: TPUMachineModel, save_path: Optional[str] = None
              ) -> bool:
    """Update mm.efficiency from real kernel timings on this device.
    Returns True when the measurements succeeded; on failure the analytic
    defaults stand and are NOT persisted (a cached guess would silently
    defeat re-measurement forever)."""
    try:
        mm.efficiency["matmul"] = max(0.05, measure_matmul_efficiency(mm))
        # per-dtype calibration: f32 GEMMs achieve a DIFFERENT fraction
        # of their (halved) peak than bf16 does of its own — the
        # "matmul:<dtype>" keys override the family factor when
        # compute_time prices that dtype (mixed-precision cost model).
        # bf16's factor IS the family default (TPU datasheet basis).
        import jax.numpy as _jnp
        mm.efficiency["matmul:float32"] = max(
            0.05, measure_matmul_efficiency(mm, dtype=_jnp.float32))
        mm.efficiency["matmul:bfloat16"] = mm.efficiency["matmul"]
        mm.efficiency["conv"] = max(0.05, measure_conv_efficiency(mm))
        mm.efficiency["elementwise"] = max(
            0.05, measure_elementwise_efficiency(mm))
        mm.efficiency["step_overhead_s"] = measure_step_overhead()
    except Exception as e:  # CPU or restricted platform: keep defaults
        import warnings
        warnings.warn(f"calibration failed, using defaults: {e}")
        return False
    if save_path:
        try:
            mm.save_calibration(save_path)
        except OSError as e:  # unwritable cache must not abort a search
            import warnings
            warnings.warn(f"could not persist calibration to "
                          f"{save_path}: {e}")
    return True


# per-device-kind efficiency factors, measured once per machine and
# persisted (the analog of the reference timing real kernels inside
# every search run, src/runtime/model.cu:20-62 — on TPU the factors are
# shape-stable so one measurement amortizes over all searches).
_CAL_MEMO: dict = {}


def cache_file(prefix: str, device_kind: str) -> str:
    """Per-machine measurement cache path (shared by the calibration
    and per-op cost caches so the root/sanitization policy lives
    once)."""
    root = os.environ.get("FLEXFLOW_TPU_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "flexflow_tpu"))
    safe = device_kind.lower().replace(" ", "_")
    return os.path.join(root, f"{prefix}_{safe}.json")


def calibration_cache_path(device_kind: str) -> str:
    return cache_file("calibration", device_kind)


def calibrated_machine_model(mesh=None, machine_file: Optional[str] = None,
                             force: bool = False) -> TPUMachineModel:
    """`default_machine_model`, with efficiency factors measured on the
    real device when one is present (VERDICT round-1 item 3: no search
    runs on the hard-coded 0.55/0.8 guesses when hardware is attached).

    Off-TPU (the forced-CPU test platform) the analytic defaults stand —
    there is no MXU/HBM to measure. Results are memoized per device kind
    in-process and persisted under ~/.cache/flexflow_tpu/ (override with
    FLEXFLOW_TPU_CACHE) so one machine measures once, ever."""
    mm = default_machine_model(mesh, machine_file=machine_file)
    try:
        import jax
        if jax.default_backend() != "tpu":
            return mm
        kind = jax.devices()[0].device_kind
    except Exception:
        return mm
    if not force and kind in _CAL_MEMO:
        mm.efficiency.update(_CAL_MEMO[kind])
        return mm
    path = calibration_cache_path(kind)
    if not force and os.path.exists(path):
        try:
            mm.load_calibration(path)
            _CAL_MEMO[kind] = dict(mm.efficiency)
            return mm
        except (OSError, json.JSONDecodeError):
            pass
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
    except OSError:
        path = None  # measure anyway; just don't persist
    if calibrate(mm, save_path=path):
        # memoize only real measurements — a failed attempt must retry
        # next time, not pin the defaults for the process lifetime
        _CAL_MEMO[kind] = dict(mm.efficiency)
    return mm
