"""Auto-parallelization search — the heart of the reference
(SURVEY.md 2.4): cost model + simulator + MCMC over per-op strategies."""
