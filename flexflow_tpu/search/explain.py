"""Explainable placement: per-op cost breakdowns, rejected
alternatives, and the HBM memory ledger.

The search picks a placement; this module says WHY. Two halves:

* :func:`explain_placement` — for every op under a (found or given)
  strategy: the chosen axis map, the priced cost decomposed into the
  simulator's task components (fwd / bwd / update / collectives /
  grad sync — the components sum to the op's priced total bit-exactly,
  gated in tests), and the top-k REJECTED candidate axis maps with
  their deltas, priced by the same `Simulator._op_cost` tiers the
  search annealed through. Plus the step-level view: simulated step
  time, the per-task-class breakdown the drift calibrator aligns
  against, and the HBM ledger below.

* HBM memory ledger — per-device byte accounting (params, optimizer
  state, activation estimate; serving adds KV pages + scale rows and
  adapter headroom) from the LIVE device buffers
  (:func:`pytree_device_bytes` reads each array's shard shape), placed
  next to the simulator's HBM-penalty input so a mis-priced memory
  term is visible before it mis-ranks a placement.
  `ServeEngine.memory_ledger` / `FFModel.memory_ledger` build these;
  tools/explain.py renders them and ci.sh gates the serve ledger
  within 5% of the live buffers.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from ..parallel.pconfig import Strategy
from .cost_model import OpCost
from .simulator import Simulator, _axis_sig

__all__ = ["explain_placement", "explain_report",
           "op_cost_components", "pytree_device_bytes"]


def op_cost_components(c: OpCost) -> Dict[str, float]:
    """One op's priced cost split into the simulator's task components
    (seconds). The reported ``total_s`` is the sum of exactly these
    values in exactly this order, so components always sum to the
    priced cost bit-exactly."""
    return {"fwd": c.fwd, "bwd": c.bwd, "update": c.update,
            "fwd_comm": c.fwd_comm, "bwd_comm": c.bwd_comm,
            "grad_sync": c.sync}


def pytree_device_bytes(tree) -> float:
    """Per-device resident bytes of the live jax arrays in `tree`:
    each array contributes its SHARD's bytes (``sharding.shard_shape``
    — a replicated array costs its full size per device, a sharded one
    its slice), which is what actually occupies one chip's HBM."""
    import jax
    total = 0.0
    for x in jax.tree_util.tree_leaves(tree):
        if x is None or not hasattr(x, "nbytes"):
            continue
        shard = None
        sharding = getattr(x, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            try:
                shard = sharding.shard_shape(x.shape)
            except Exception:
                shard = None
        if shard is not None:
            total += float(math.prod(shard)) * x.dtype.itemsize
        else:
            total += float(x.nbytes)
    return total


def explain_placement(model, mesh=None, strategy: Optional[Strategy]
                      = None, simulator: Optional[Simulator] = None,
                      top_k: int = 3) -> dict:
    """Why the placement looks the way it does: per-op chosen config,
    cost breakdown, and the top-k rejected alternatives, plus the
    step-level totals (simulated step time, per-class breakdown, HBM
    accounting vs the machine's capacity).

    `strategy` defaults to the model's current strategy (the search
    winner after optimize); `simulator` defaults to a fresh Simulator
    on the model's machine model — pass the search's own simulator to
    explain from its exact calibrated state."""
    from .mcmc import candidate_maps
    from ..parallel.pconfig import OpStrategy

    mesh = mesh if mesh is not None else model.mesh
    if mesh is None:
        from ..parallel.mesh import make_mesh
        mesh = make_mesh((1,), ("data",))
    sim = simulator or Simulator(model, mesh)
    strategy = (strategy if strategy is not None
                else (model.strategy or Strategy()))
    cfg = model.config

    ops: List[dict] = []
    for i, op in enumerate(model.ops):
        s = strategy.for_op(op.name)
        c = sim._op_cost(op, strategy)
        comps = op_cost_components(c)
        chosen_sig = _axis_sig(s)
        alts = []
        for cand in candidate_maps(op, mesh, cfg, op_index=i):
            alt = OpStrategy(dict(cand))
            sig = _axis_sig(alt)
            if sig == chosen_sig:
                continue
            ac = sim._op_cost_for(op, alt, sig)
            a_comps = op_cost_components(ac)
            a_total = sum(a_comps.values())
            alts.append({
                "axis_map": {k: str(v) for k, v in cand.items()},
                "total_s": a_total,
                "components": a_comps,
                "mem_bytes": ac.mem,
            })
        alts.sort(key=lambda a: a["total_s"])
        total = sum(comps.values())
        ops.append({
            "op": op.name,
            "op_type": op.op_type,
            "chosen": {k: str(v) for k, v in s.axis_map.items()},
            "total_s": total,
            "components": comps,
            "mem_bytes": c.mem,
            "alternatives": [
                {**a, "delta_s": a["total_s"] - total}
                for a in alts[:max(0, int(top_k))]],
            "rejected_candidates": len(alts),
        })

    mem_per_dev = sim.memory_per_device(strategy)
    hbm = float(sim.mm.spec.hbm_capacity)
    return {
        "mesh": dict(mesh.shape),
        "step_time_s": sim.simulate(strategy),
        "step_breakdown_s": sim.step_breakdown(strategy),
        "ops": ops,
        "memory": {
            "sim_bytes_per_device": mem_per_dev,
            "hbm_capacity_bytes": hbm,
            "hbm_utilization": mem_per_dev / hbm if hbm else 0.0,
            "hbm_penalty_s": sim.mm.memory_penalty(mem_per_dev),
        },
    }


def explain_report(info: dict, max_alts: int = 2) -> str:
    """Human rendering of :func:`explain_placement`: one row per op
    (chosen config, cost, dominant component) with its best rejected
    alternatives indented underneath."""
    lines = [
        f"placement on mesh {info['mesh']}: simulated step "
        f"{info['step_time_s']*1e3:.3f} ms",
        "breakdown: " + " ".join(
            f"{k}={v*1e3:.3f}ms"
            for k, v in info["step_breakdown_s"].items() if v),
    ]
    mem = info["memory"]
    lines.append(
        f"hbm: {mem['sim_bytes_per_device']/2**20:.1f} MiB/device of "
        f"{mem['hbm_capacity_bytes']/2**30:.0f} GiB "
        f"({mem['hbm_utilization']:.1%}"
        + (f", penalty {mem['hbm_penalty_s']*1e3:.3f} ms"
           if mem["hbm_penalty_s"] else "")
        + ")")
    lines.append(f"{'op':28s} {'type':18s} {'config':26s} "
                 f"{'cost ms':>9s} {'mem MiB':>8s}")
    for o in info["ops"]:
        chosen = ",".join(f"{k}->{v}" for k, v in o["chosen"].items()) \
            or "replicated"
        lines.append(
            f"{o['op']:28s} {o['op_type']:18s} {chosen:26s} "
            f"{o['total_s']*1e3:>9.4f} {o['mem_bytes']/2**20:>8.2f}")
        for a in o["alternatives"][:max_alts]:
            amap = ",".join(f"{k}->{v}"
                            for k, v in a["axis_map"].items()) \
                or "replicated"
            lines.append(
                f"{'':28s} {'rejected':18s} {amap:26s} "
                f"{a['total_s']*1e3:>9.4f} "
                f"(+{a['delta_s']*1e3:.4f} ms)")
    return "\n".join(lines)
