"""Inference-placement search: the paper's loop closed for serving.

The source paper's core move — per-op parallel configs discovered by a
simulator-driven MCMC search — has only ever priced TRAINING steps
here (mcmc.optimize over the op graph). This module applies the same
machinery to the serve program: candidates are (tensor-parallel
degree, physical axis assignment) pairs for the ONE mixed
prefill+decode step (docs/serving.md "Sharded serving"), costs come
from the serve task graph (cost_model.serve_step_tasks) run through
the serve event loop (simulator.simulate_serve_step) on the same
TPUMachineModel the training search prices against, and the annealing
loop is the reference's Metropolis walk (model.cc:1807-1903 idiom,
mirroring mcmc._anneal) over the placement space.

``optimize_serve`` is what ``--serve-mesh auto`` resolves through
(ServeEngine._resolve_serve_mesh): it returns the placement whose
simulated decode step is fastest, with the budget-sized prefill chunk
as the tiebreak-weighted second workload. Costs persist in the SAME
CostCache as op costs, scoped by a machine fingerprint that folds the
serve signature (cost_cache.machine_fingerprint(serve=...)) — a
placement or KV-dtype flip is a guaranteed cache miss.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Tuple

from .cost_model import ServeArch, kv_handoff_bytes
from .machine_model import TPUMachineModel
from .simulator import simulate_serve_step

# objective weights: serving steady state is decode-dominated (every
# request decodes for its whole output length but prefills once), so
# the decode step carries the objective and the prefill chunk enters
# at a fraction — enough that a placement which wrecks prefill cannot
# win on decode alone.
PREFILL_WEIGHT = 0.25


@dataclasses.dataclass(frozen=True)
class ServePlacement:
    """One serve placement the search priced (the winner when returned
    by optimize_serve): the tensor-parallel degree the engine shards
    the mixed program to, the physical torus dims the serve axis rides
    (() = one flat ICI ring), and the simulated steady-state costs."""
    tensor_parallel: int
    axis_dims: Tuple[int, ...]
    decode_step_s: float
    prefill_step_s: float
    cost: float
    # every candidate degree's best decode step (axis optimized away) —
    # what serve_bench renders as the t-sweep and the speedup gate reads
    decode_by_degree: Dict[int, float] = dataclasses.field(
        default_factory=dict)
    fingerprint: str = ""
    # convergence diagnostics of the placement walk
    # (search/trace.SearchTrace.summary(); None with tracing off)
    trace: Optional[dict] = None

    def speedup_vs_single(self) -> float:
        base = self.decode_by_degree.get(1)
        if not base or not self.decode_step_s:
            return 1.0
        return base / self.decode_step_s


def candidate_degrees(arch: ServeArch, num_devices: int) -> List[int]:
    """Tensor degrees the engine can actually run: divisors of the
    head count, bounded by the device count (head sharding is the
    backbone — ff/vocab pad, heads cannot)."""
    n = max(1, int(num_devices))
    return [t for t in range(1, n + 1)
            if arch.num_heads % t == 0]


def axis_assignments(mm: TPUMachineModel, t: int) -> List[Tuple[int, ...]]:
    """Physical layouts the serve axis could take on this machine: the
    flat single ring always, plus every contiguous run of the spec's
    ICI torus dims whose product is exactly t (a k-dim assignment runs
    ring phases over k link sets concurrently —
    machine_model._phys)."""
    out: List[Tuple[int, ...]] = [()]
    dims = tuple(getattr(mm.spec, "ici_torus_dims", ()) or ())
    for i in range(len(dims)):
        prod = 1
        for j in range(i, len(dims)):
            prod *= dims[j]
            if prod == t:
                out.append(dims[i:j + 1])
            if prod >= t:
                break
    return out


def _serve_fingerprint(mm: TPUMachineModel, arch: ServeArch) -> str:
    # serve_v2: LoRA adapter pricing (adapter_rank/adapter_slots fold
    # in) — rows priced by the pre-adapter formulas can never
    # resurrect into an adapter-aware search, and vice versa
    from .cost_cache import machine_fingerprint
    return machine_fingerprint(
        mm, serve=("serve_v2", arch.kv_dtype, arch.act_dtype,
                   arch.kv_itemsize, arch.act_itemsize,
                   arch.param_itemsize, arch.adapter_rank,
                   arch.adapter_slots))


def price_placement(arch: ServeArch, t: int, mm: TPUMachineModel,
                    axis_dims: Tuple[int, ...] = (),
                    cache=None, fingerprint: str = ""
                    ) -> Tuple[float, float]:
    """(decode_step_s, prefill_step_s) of one candidate, through the
    persistent cost cache when given: rows are stored OpCost-shaped
    (decode in fwd, prefill in bwd) under a key carrying the placement
    AND the full arch signature, inside a fingerprint carrying the
    serve dtypes — either flip misses."""
    key = None
    if cache is not None:
        key = cache.entry_key("serve_step", (t, tuple(axis_dims)),
                              extra=arch.signature())
        row = cache.get(fingerprint, key)
        if row is not None:
            return row.fwd, row.bwd
    dec = simulate_serve_step(arch, t, mm, axis_dims=axis_dims)
    pre = simulate_serve_step(arch, t, mm, axis_dims=axis_dims,
                              lanes=arch.prefill_lanes)
    if cache is not None:
        from .cost_model import OpCost
        cache.put(fingerprint, key,
                  OpCost(fwd=dec, bwd=pre, fwd_comm=0.0, bwd_comm=0.0,
                         sync=0.0, mem=0.0))
    return dec, pre


def optimize_serve(arch: ServeArch, num_devices: int, *,
                   mm: Optional[TPUMachineModel] = None,
                   config=None, budget: int = 64, alpha: float = 0.05,
                   seed: Optional[int] = None,
                   disaggregated: bool = False):
    """Pick the serve placement by simulated annealing over
    (degree, axis assignment) — the reference's Metropolis walk with
    the same relative-delta acceptance as mcmc._anneal — then return
    the best placement visited with its per-degree decode table.

    `config` (an FFConfig) supplies the machine model file, cost-cache
    path and seed the training search uses, so `--serve-mesh auto`
    prices serving on exactly the machine the training side was
    calibrated against. The space is small (divisor degrees × torus
    runs), so the default budget walks it to the optimum; the walk —
    not enumeration — is kept so richer placement spaces (replica
    counts, per-layer degrees) extend without restructuring.

    ``disaggregated=True`` searches the SPLIT serving space instead
    (prefill:decode engine ratio × per-role tensor degree, the page-
    handoff link priced on the host link) and returns a
    :class:`DisaggPlacement` — see :func:`optimize_serve_disagg`."""
    if disaggregated:
        return optimize_serve_disagg(arch, num_devices, mm=mm,
                                     config=config, seed=seed)
    if mm is None:
        from .machine_model import default_machine_model
        mm = default_machine_model(
            machine_file=getattr(config, "machine_model_file", None)
            if config is not None else None)
    if seed is None:
        seed = int(getattr(config, "seed", 0) or 0) \
            if config is not None else 0
    cache = None
    fingerprint = ""
    if config is None or getattr(config, "search_cost_cache", True):
        from .cost_cache import CostCache
        cache = CostCache.open(
            (getattr(config, "cost_cache_file", None) or None)
            if config is not None else None)
        fingerprint = _serve_fingerprint(mm, arch)

    degrees = candidate_degrees(arch, num_devices)
    space: List[Tuple[int, Tuple[int, ...]]] = [
        (t, dims) for t in degrees for dims in axis_assignments(mm, t)]

    def cost_of(cand) -> Tuple[float, float, float]:
        t, dims = cand
        dec, pre = price_placement(arch, t, mm, dims, cache=cache,
                                   fingerprint=fingerprint)
        return dec + PREFILL_WEIGHT * pre, dec, pre

    rng = random.Random(seed)
    walk_budget = max(len(space), int(budget))
    trace = None
    if config is None or getattr(config, "search_trace", True):
        from .trace import SearchTrace
        trace = SearchTrace(budget=walk_budget)
    cur = (1, ())
    cur_cost, cur_dec, cur_pre = cost_of(cur)
    best, best_cost = cur, cur_cost
    best_dec, best_pre = cur_dec, cur_pre
    if trace is not None:
        trace.record_best(-1, 0, best_cost)
    # every legal degree is priced once up front (flat ring) so the
    # returned per-degree table is complete — the paper's exhaustive
    # per-op config enumeration, affordable here because degrees are
    # few; the walk then also explores axis assignments
    decode_by_degree: Dict[int, float] = {}
    for t in degrees:
        c, dec, pre = cost_of((t, ()))
        decode_by_degree[t] = dec
        if c < best_cost:
            best, best_cost = (t, ()), c
            best_dec, best_pre = dec, pre
            if trace is not None:
                trace.record_best(-1, 0, best_cost)
    for it in range(walk_budget):
        nxt = space[rng.randrange(len(space))]
        if nxt == cur:
            continue
        nxt_cost, nxt_dec, nxt_pre = cost_of(nxt)
        t = nxt[0]
        if nxt_dec < decode_by_degree.get(t, float("inf")):
            decode_by_degree[t] = nxt_dec
        delta = nxt_cost - cur_cost
        temp = alpha * cur_cost
        accepted = delta <= 0 or rng.random() < math.exp(
            -delta / max(1e-12, temp))
        if accepted:
            cur, cur_cost = nxt, nxt_cost
            if cur_cost < best_cost:
                best, best_cost = cur, cur_cost
                best_dec, best_pre = nxt_dec, nxt_pre
                if trace is not None:
                    trace.record_best(it, 0, best_cost)
        if trace is not None:  # observation only, after the decision —
            # traced and untraced walks consume the RNG identically
            trace.record(it, 0, "serve_place",
                         f"t={t} dims={tuple(nxt[1])}", delta,
                         accepted, temp, "serve")
    if cache is not None:
        cache.flush()
    return ServePlacement(
        tensor_parallel=best[0], axis_dims=tuple(best[1]),
        decode_step_s=best_dec, prefill_step_s=best_pre,
        cost=best_cost, decode_by_degree=dict(
            sorted(decode_by_degree.items())),
        fingerprint=fingerprint,
        trace=trace.summary() if trace is not None else None)


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode placement (serve/disagg.py's search half)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DisaggPlacement:
    """One disaggregated serving placement the search priced: how many
    dedicated prefill vs decode engines to run (at which per-role
    tensor degrees), with the page-handoff link costed on the host
    link. ``ratio_table`` maps "p:d" engine ratios to their best
    steady-state per-request seconds (per-role degrees optimized away)
    — the disaggregated mirror of ServePlacement.decode_by_degree."""

    prefill_engines: int
    prefill_tensor: int
    decode_engines: int
    decode_tensor: int
    # steady-state components of the winning candidate (seconds)
    decode_step_s: float        # one decode-engine step — the TPOT floor
    prefill_step_s: float       # one budget-wide prefill-engine step
    transfer_s: float           # one request's page handoff on the link
    bottleneck_s: float         # slowest pipeline stage, per request
    cost: float
    # "p:d" -> best per-request seconds at that engine ratio
    ratio_table: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # the unified baseline at the same device count (optimize_serve's
    # winner run as num_devices/t data-parallel replicas): its TPOT is
    # the full mixed-width step — what the A/B's reduction is against
    unified_tpot_s: float = 0.0
    unified_per_request_s: float = 0.0
    fingerprint: str = ""

    @property
    def ratio(self) -> str:
        return f"{self.prefill_engines}:{self.decode_engines}"

    def tpot_reduction_vs_unified(self) -> float:
        """Simulated TPOT win of the split: the unified engine's
        mixed-width step over the decode engine's decode-only step."""
        if not self.decode_step_s or not self.unified_tpot_s:
            return 1.0
        return self.unified_tpot_s / self.decode_step_s


def price_disagg_candidate(arch: ServeArch, t_pre: int, t_dec: int,
                           mm: TPUMachineModel, *, cache=None,
                           fingerprint: str = ""
                           ) -> Tuple[float, float, float]:
    """(prefill_step_s, decode_step_s, transfer_s) of one per-role
    degree pair, through the persistent cost cache when given.

    The prefill engine's step is the budget-wide mixed program at
    ``t_pre``; the decode engine's step is its REAL fixed program —
    ``decode_lanes`` query lanes plus the ``handoff_stub_lanes``
    prefill stub that recomputes handoff tails (no full prefill
    budget riding along, the whole point of the split) — at
    ``t_dec``, priced WITH the
    steady-state page-handoff load importing beside it
    (cost_model.serve_step_tasks): the decode engine turns over its
    ``decode_lanes`` requests every ``decode_tokens`` steps, so each
    step imports ``context * decode_lanes / decode_tokens`` tokens'
    pages on average; the transfer term itself is the host-link
    seconds of one full context's pages — what the ratio balance
    weighs against freed compute. Cached rows carry the full arch
    signature (kv dtype/itemsize included), so a KV-dtype flip is a
    guaranteed miss AND a changed transfer price."""
    key = None
    if cache is not None:
        key = cache.entry_key("serve_disagg", (t_pre, t_dec),
                              extra=arch.signature())
        row = cache.get(fingerprint, key)
        if row is not None:
            return row.fwd, row.bwd, row.sync
    pre = simulate_serve_step(arch, t_pre, mm,
                              lanes=arch.prefill_lanes)
    per_step_tokens = max(1, round(
        arch.context * arch.decode_lanes
        / max(1, getattr(arch, "decode_tokens", 64))))
    dec_lanes = arch.decode_lanes + int(
        getattr(arch, "handoff_stub_lanes", 32))
    dec = simulate_serve_step(arch, t_dec, mm, lanes=dec_lanes,
                              transfer_tokens=per_step_tokens)
    xfer = mm.host_transfer(kv_handoff_bytes(arch))
    if cache is not None:
        from .cost_model import OpCost
        cache.put(fingerprint, key,
                  OpCost(fwd=pre, bwd=dec, fwd_comm=0.0, bwd_comm=0.0,
                         sync=xfer, mem=0.0))
    return pre, dec, xfer


def optimize_serve_disagg(arch: ServeArch, num_devices: int, *,
                          mm: Optional[TPUMachineModel] = None,
                          config=None,
                          seed: Optional[int] = None
                          ) -> DisaggPlacement:
    """Pick the prefill:decode split — engine counts × per-role tensor
    degrees — whose steady-state per-request bottleneck is smallest:
    the SOAP don't-hand-tune-it discipline applied to the
    disaggregation axis (ROADMAP).

    Steady state under mixed traffic: every request prefills its
    ``context`` tokens in budget-sized chunks on SOME prefill engine,
    ships its pages over the host link once, and decodes
    ``decode_tokens`` tokens on a decode-lane of SOME decode engine.
    Each stage's per-request seconds:

      prefill  = prefill_step_s * ceil(context/prefill_lanes) / p
      transfer = host_transfer(kv_handoff_bytes) / p   (one DMA link
                 per prefill engine's host)
      decode   = decode_step_s * decode_tokens / decode_lanes / d

    and the pipeline sustains 1/max(stages) requests per second. The
    objective is that bottleneck plus ``PREFILL_WEIGHT`` × the decode
    step (TTFT already carries the prefill weight in the unified
    objective; here the extra term keeps a ratio that wrecks TPOT from
    winning on raw throughput). The space is small (ratios × divisor
    degrees), so it is enumerated exhaustively — the per-op
    exhaustive-config half of the reference search — and the full
    ratio table is returned the way optimize_serve returns the
    per-degree decode table."""
    if mm is None:
        from .machine_model import default_machine_model
        mm = default_machine_model(
            machine_file=getattr(config, "machine_model_file", None)
            if config is not None else None)
    n = max(2, int(num_devices))
    cache = None
    fingerprint = ""
    if config is None or getattr(config, "search_cost_cache", True):
        from .cost_cache import CostCache
        cache = CostCache.open(
            (getattr(config, "cost_cache_file", None) or None)
            if config is not None else None)
        fingerprint = _serve_fingerprint(mm, arch)

    degrees = candidate_degrees(arch, n)
    chunks_per_prompt = max(1.0, math.ceil(
        arch.context / max(1, arch.prefill_lanes)))
    dec_tokens = max(1, int(getattr(arch, "decode_tokens", 64)))

    best = None
    best_cost = float("inf")
    ratio_table: Dict[str, float] = {}
    # each role's step cost depends on ITS degree only (the transfer
    # term on neither), so one pricing per degree covers every
    # (t_pre, t_dec) pair — O(D) simulations, not O(D^2)
    priced = {t: price_disagg_candidate(arch, t, t, mm, cache=cache,
                                        fingerprint=fingerprint)
              for t in degrees}
    for t_pre in degrees:
        pre = priced[t_pre][0]
        for t_dec in degrees:
            dec, xfer = priced[t_dec][1], priced[t_dec][2]
            p_max = (n - t_dec) // t_pre
            if p_max < 1:
                continue
            for p in range(1, p_max + 1):
                d = (n - p * t_pre) // t_dec
                if d < 1:
                    continue
                stage_pre = pre * chunks_per_prompt / p
                stage_xfer = xfer / p
                stage_dec = dec * dec_tokens / max(
                    1, arch.decode_lanes) / d
                bottleneck = max(stage_pre, stage_xfer, stage_dec)
                cost = bottleneck + PREFILL_WEIGHT * dec
                ratio = f"{p}:{d}"
                if bottleneck < ratio_table.get(ratio, float("inf")):
                    ratio_table[ratio] = bottleneck
                if cost < best_cost:
                    best_cost = cost
                    best = (p, t_pre, d, t_dec, pre, dec, xfer,
                            bottleneck)
    if best is None:
        raise ValueError(
            f"no disaggregated placement fits {num_devices} devices "
            f"(need >= 1 prefill + 1 decode engine)")

    # the unified baseline at the same device count: optimize_serve's
    # winner replicated data-parallel, its TPOT the FULL mixed-width
    # step (decode lanes pay for the prefill budget every step — the
    # interference disaggregation removes)
    uni = optimize_serve(arch, n, mm=mm, config=config, seed=seed)
    replicas = max(1, n // max(1, uni.tensor_parallel))
    uni_tpot = simulate_serve_step(
        arch, uni.tensor_parallel, mm, axis_dims=uni.axis_dims,
        lanes=arch.decode_lanes + arch.prefill_lanes)
    uni_per_req = (uni_tpot * dec_tokens / max(1, arch.decode_lanes)
                   + uni.prefill_step_s * chunks_per_prompt) / replicas

    if cache is not None:
        cache.flush()
    p, t_pre, d, t_dec, pre, dec, xfer, bottleneck = best

    def _ratio_key(r: str) -> Tuple[int, int]:
        a, b = r.split(":")
        return int(a), int(b)

    return DisaggPlacement(
        prefill_engines=p, prefill_tensor=t_pre,
        decode_engines=d, decode_tensor=t_dec,
        decode_step_s=dec, prefill_step_s=pre, transfer_s=xfer,
        bottleneck_s=bottleneck, cost=best_cost,
        ratio_table=dict(sorted(ratio_table.items(),
                                key=lambda kv: _ratio_key(kv[0]))),
        unified_tpot_s=uni_tpot, unified_per_request_s=uni_per_req,
        fingerprint=fingerprint)
