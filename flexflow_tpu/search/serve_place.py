"""Inference-placement search: the paper's loop closed for serving.

The source paper's core move — per-op parallel configs discovered by a
simulator-driven MCMC search — has only ever priced TRAINING steps
here (mcmc.optimize over the op graph). This module applies the same
machinery to the serve program: candidates are (tensor-parallel
degree, physical axis assignment) pairs for the ONE mixed
prefill+decode step (docs/serving.md "Sharded serving"), costs come
from the serve task graph (cost_model.serve_step_tasks) run through
the serve event loop (simulator.simulate_serve_step) on the same
TPUMachineModel the training search prices against, and the annealing
loop is the reference's Metropolis walk (model.cc:1807-1903 idiom,
mirroring mcmc._anneal) over the placement space.

``optimize_serve`` is what ``--serve-mesh auto`` resolves through
(ServeEngine._resolve_serve_mesh): it returns the placement whose
simulated decode step is fastest, with the budget-sized prefill chunk
as the tiebreak-weighted second workload. Costs persist in the SAME
CostCache as op costs, scoped by a machine fingerprint that folds the
serve signature (cost_cache.machine_fingerprint(serve=...)) — a
placement or KV-dtype flip is a guaranteed cache miss.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Tuple

from .cost_model import ServeArch
from .machine_model import TPUMachineModel
from .simulator import simulate_serve_step

# objective weights: serving steady state is decode-dominated (every
# request decodes for its whole output length but prefills once), so
# the decode step carries the objective and the prefill chunk enters
# at a fraction — enough that a placement which wrecks prefill cannot
# win on decode alone.
PREFILL_WEIGHT = 0.25


@dataclasses.dataclass(frozen=True)
class ServePlacement:
    """One serve placement the search priced (the winner when returned
    by optimize_serve): the tensor-parallel degree the engine shards
    the mixed program to, the physical torus dims the serve axis rides
    (() = one flat ICI ring), and the simulated steady-state costs."""
    tensor_parallel: int
    axis_dims: Tuple[int, ...]
    decode_step_s: float
    prefill_step_s: float
    cost: float
    # every candidate degree's best decode step (axis optimized away) —
    # what serve_bench renders as the t-sweep and the speedup gate reads
    decode_by_degree: Dict[int, float] = dataclasses.field(
        default_factory=dict)
    fingerprint: str = ""
    # convergence diagnostics of the placement walk
    # (search/trace.SearchTrace.summary(); None with tracing off)
    trace: Optional[dict] = None

    def speedup_vs_single(self) -> float:
        base = self.decode_by_degree.get(1)
        if not base or not self.decode_step_s:
            return 1.0
        return base / self.decode_step_s


def candidate_degrees(arch: ServeArch, num_devices: int) -> List[int]:
    """Tensor degrees the engine can actually run: divisors of the
    head count, bounded by the device count (head sharding is the
    backbone — ff/vocab pad, heads cannot)."""
    n = max(1, int(num_devices))
    return [t for t in range(1, n + 1)
            if arch.num_heads % t == 0]


def axis_assignments(mm: TPUMachineModel, t: int) -> List[Tuple[int, ...]]:
    """Physical layouts the serve axis could take on this machine: the
    flat single ring always, plus every contiguous run of the spec's
    ICI torus dims whose product is exactly t (a k-dim assignment runs
    ring phases over k link sets concurrently —
    machine_model._phys)."""
    out: List[Tuple[int, ...]] = [()]
    dims = tuple(getattr(mm.spec, "ici_torus_dims", ()) or ())
    for i in range(len(dims)):
        prod = 1
        for j in range(i, len(dims)):
            prod *= dims[j]
            if prod == t:
                out.append(dims[i:j + 1])
            if prod >= t:
                break
    return out


def _serve_fingerprint(mm: TPUMachineModel, arch: ServeArch) -> str:
    from .cost_cache import machine_fingerprint
    return machine_fingerprint(
        mm, serve=("serve_v1", arch.kv_dtype, arch.act_dtype,
                   arch.kv_itemsize, arch.act_itemsize,
                   arch.param_itemsize))


def price_placement(arch: ServeArch, t: int, mm: TPUMachineModel,
                    axis_dims: Tuple[int, ...] = (),
                    cache=None, fingerprint: str = ""
                    ) -> Tuple[float, float]:
    """(decode_step_s, prefill_step_s) of one candidate, through the
    persistent cost cache when given: rows are stored OpCost-shaped
    (decode in fwd, prefill in bwd) under a key carrying the placement
    AND the full arch signature, inside a fingerprint carrying the
    serve dtypes — either flip misses."""
    key = None
    if cache is not None:
        key = cache.entry_key("serve_step", (t, tuple(axis_dims)),
                              extra=arch.signature())
        row = cache.get(fingerprint, key)
        if row is not None:
            return row.fwd, row.bwd
    dec = simulate_serve_step(arch, t, mm, axis_dims=axis_dims)
    pre = simulate_serve_step(arch, t, mm, axis_dims=axis_dims,
                              lanes=arch.prefill_lanes)
    if cache is not None:
        from .cost_model import OpCost
        cache.put(fingerprint, key,
                  OpCost(fwd=dec, bwd=pre, fwd_comm=0.0, bwd_comm=0.0,
                         sync=0.0, mem=0.0))
    return dec, pre


def optimize_serve(arch: ServeArch, num_devices: int, *,
                   mm: Optional[TPUMachineModel] = None,
                   config=None, budget: int = 64, alpha: float = 0.05,
                   seed: Optional[int] = None) -> ServePlacement:
    """Pick the serve placement by simulated annealing over
    (degree, axis assignment) — the reference's Metropolis walk with
    the same relative-delta acceptance as mcmc._anneal — then return
    the best placement visited with its per-degree decode table.

    `config` (an FFConfig) supplies the machine model file, cost-cache
    path and seed the training search uses, so `--serve-mesh auto`
    prices serving on exactly the machine the training side was
    calibrated against. The space is small (divisor degrees × torus
    runs), so the default budget walks it to the optimum; the walk —
    not enumeration — is kept so richer placement spaces (replica
    counts, per-layer degrees) extend without restructuring."""
    if mm is None:
        from .machine_model import default_machine_model
        mm = default_machine_model(
            machine_file=getattr(config, "machine_model_file", None)
            if config is not None else None)
    if seed is None:
        seed = int(getattr(config, "seed", 0) or 0) \
            if config is not None else 0
    cache = None
    fingerprint = ""
    if config is None or getattr(config, "search_cost_cache", True):
        from .cost_cache import CostCache
        cache = CostCache.open(
            (getattr(config, "cost_cache_file", None) or None)
            if config is not None else None)
        fingerprint = _serve_fingerprint(mm, arch)

    degrees = candidate_degrees(arch, num_devices)
    space: List[Tuple[int, Tuple[int, ...]]] = [
        (t, dims) for t in degrees for dims in axis_assignments(mm, t)]

    def cost_of(cand) -> Tuple[float, float, float]:
        t, dims = cand
        dec, pre = price_placement(arch, t, mm, dims, cache=cache,
                                   fingerprint=fingerprint)
        return dec + PREFILL_WEIGHT * pre, dec, pre

    rng = random.Random(seed)
    walk_budget = max(len(space), int(budget))
    trace = None
    if config is None or getattr(config, "search_trace", True):
        from .trace import SearchTrace
        trace = SearchTrace(budget=walk_budget)
    cur = (1, ())
    cur_cost, cur_dec, cur_pre = cost_of(cur)
    best, best_cost = cur, cur_cost
    best_dec, best_pre = cur_dec, cur_pre
    if trace is not None:
        trace.record_best(-1, 0, best_cost)
    # every legal degree is priced once up front (flat ring) so the
    # returned per-degree table is complete — the paper's exhaustive
    # per-op config enumeration, affordable here because degrees are
    # few; the walk then also explores axis assignments
    decode_by_degree: Dict[int, float] = {}
    for t in degrees:
        c, dec, pre = cost_of((t, ()))
        decode_by_degree[t] = dec
        if c < best_cost:
            best, best_cost = (t, ()), c
            best_dec, best_pre = dec, pre
            if trace is not None:
                trace.record_best(-1, 0, best_cost)
    for it in range(walk_budget):
        nxt = space[rng.randrange(len(space))]
        if nxt == cur:
            continue
        nxt_cost, nxt_dec, nxt_pre = cost_of(nxt)
        t = nxt[0]
        if nxt_dec < decode_by_degree.get(t, float("inf")):
            decode_by_degree[t] = nxt_dec
        delta = nxt_cost - cur_cost
        temp = alpha * cur_cost
        accepted = delta <= 0 or rng.random() < math.exp(
            -delta / max(1e-12, temp))
        if accepted:
            cur, cur_cost = nxt, nxt_cost
            if cur_cost < best_cost:
                best, best_cost = cur, cur_cost
                best_dec, best_pre = nxt_dec, nxt_pre
                if trace is not None:
                    trace.record_best(it, 0, best_cost)
        if trace is not None:  # observation only, after the decision —
            # traced and untraced walks consume the RNG identically
            trace.record(it, 0, "serve_place",
                         f"t={t} dims={tuple(nxt[1])}", delta,
                         accepted, temp, "serve")
    if cache is not None:
        cache.flush()
    return ServePlacement(
        tensor_parallel=best[0], axis_dims=tuple(best[1]),
        decode_step_s=best_dec, prefill_step_s=best_pre,
        cost=best_cost, decode_by_degree=dict(
            sorted(decode_by_degree.items())),
        fingerprint=fingerprint,
        trace=trace.summary() if trace is not None else None)
