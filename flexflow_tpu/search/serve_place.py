"""Inference-placement search: the paper's loop closed for serving.

The source paper's core move — per-op parallel configs discovered by a
simulator-driven MCMC search — has only ever priced TRAINING steps
here (mcmc.optimize over the op graph). This module applies the same
machinery to the serve program: candidates are (tensor-parallel
degree, physical axis assignment) pairs for the ONE mixed
prefill+decode step (docs/serving.md "Sharded serving"), costs come
from the serve task graph (cost_model.serve_step_tasks) run through
the serve event loop (simulator.simulate_serve_step) on the same
TPUMachineModel the training search prices against, and the annealing
loop is the reference's Metropolis walk (model.cc:1807-1903 idiom,
mirroring mcmc._anneal) over the placement space.

``optimize_serve`` is what ``--serve-mesh auto`` resolves through
(ServeEngine._resolve_serve_mesh): it returns the placement whose
simulated decode step is fastest, with the budget-sized prefill chunk
as the tiebreak-weighted second workload. Costs persist in the SAME
CostCache as op costs, scoped by a machine fingerprint that folds the
serve signature (cost_cache.machine_fingerprint(serve=...)) — a
placement or KV-dtype flip is a guaranteed cache miss.

``optimize_serve_mesh`` closes the search at the POOL level — the 2-D
(tensor x data) space a ``--serve-replicas auto`` ReplicaPool boots
from: one walk over (tensor degree, replica count, torus-axis
assignment for each) with t*r <= the device budget, priced by a
goodput-under-SLO objective that composes the per-replica step price
with a traffic model (arrival split across replicas, prefix-affinity
hit discount, HBM feasibility per degree from serve_device_bytes —
infeasible degrees rejected, not penalized). See docs/search.md
"2-D serve mesh".
"""

from __future__ import annotations

import dataclasses
import math
import random
import warnings
from typing import Dict, List, Optional, Tuple

from .cost_model import ServeArch, kv_handoff_bytes, serve_device_bytes
from .machine_model import TPUMachineModel
from .simulator import simulate_serve_step

# objective weights: serving steady state is decode-dominated (every
# request decodes for its whole output length but prefills once), so
# the decode step carries the objective and the prefill chunk enters
# at a fraction — enough that a placement which wrecks prefill cannot
# win on decode alone.
PREFILL_WEIGHT = 0.25


@dataclasses.dataclass(frozen=True)
class ServePlacement:
    """One serve placement the search priced (the winner when returned
    by optimize_serve): the tensor-parallel degree the engine shards
    the mixed program to, the physical torus dims the serve axis rides
    (() = one flat ICI ring), and the simulated steady-state costs."""
    tensor_parallel: int
    axis_dims: Tuple[int, ...]
    decode_step_s: float
    prefill_step_s: float
    cost: float
    # every candidate degree's best decode step (axis optimized away) —
    # what serve_bench renders as the t-sweep and the speedup gate reads
    decode_by_degree: Dict[int, float] = dataclasses.field(
        default_factory=dict)
    fingerprint: str = ""
    # convergence diagnostics of the placement walk
    # (search/trace.SearchTrace.summary(); None with tracing off)
    trace: Optional[dict] = None

    def speedup_vs_single(self) -> float:
        base = self.decode_by_degree.get(1)
        if base is None:
            # a partial-budget search (or a head count not divisible
            # by 1 — impossible, but a fixed-degree table) can return
            # a table without the t=1 baseline; the ratio degrades to
            # 1.0 so report renderers keep working
            warnings.warn(
                "serve decode table has no t=1 baseline; reporting "
                "speedup_vs_single as 1.0x",
                RuntimeWarning, stacklevel=2)
            return 1.0
        if not base or not self.decode_step_s:
            return 1.0
        return base / self.decode_step_s


def candidate_degrees(arch: ServeArch, num_devices: int) -> List[int]:
    """Tensor degrees the engine can actually run: divisors of the
    head count, bounded by the device count (head sharding is the
    backbone — ff/vocab pad, heads cannot)."""
    n = max(1, int(num_devices))
    return [t for t in range(1, n + 1)
            if arch.num_heads % t == 0]


def axis_assignments(mm: TPUMachineModel, t: int) -> List[Tuple[int, ...]]:
    """Physical layouts the serve axis could take on this machine: the
    flat single ring always, plus every contiguous run of the spec's
    ICI torus dims whose product is exactly t (a k-dim assignment runs
    ring phases over k link sets concurrently — machine_model._phys).
    Deduplicated: on a square/cubic torus symmetric runs produce the
    SAME dims tuple (e.g. (4, 4) yields (4,) twice at t=4) and the
    cost model prices dims, not positions — duplicates would only
    burn walk proposals on candidates already visited."""
    out: List[Tuple[int, ...]] = [()]
    seen = {()}
    dims = tuple(getattr(mm.spec, "ici_torus_dims", ()) or ())
    for i in range(len(dims)):
        prod = 1
        for j in range(i, len(dims)):
            prod *= dims[j]
            if prod == t:
                run = dims[i:j + 1]
                if run not in seen:
                    seen.add(run)
                    out.append(run)
            if prod >= t:
                break
    return out


def _serve_signature(arch: ServeArch) -> Tuple:
    # serve_v2: LoRA adapter pricing (adapter_rank/adapter_slots fold
    # in) — rows priced by the pre-adapter formulas can never
    # resurrect into an adapter-aware search, and vice versa
    return ("serve_v2", arch.kv_dtype, arch.act_dtype,
            arch.kv_itemsize, arch.act_itemsize,
            arch.param_itemsize, arch.adapter_rank,
            arch.adapter_slots)


def _serve_fingerprint(mm: TPUMachineModel, arch: ServeArch) -> str:
    from .cost_cache import machine_fingerprint
    return machine_fingerprint(mm, serve=_serve_signature(arch))


def price_placement(arch: ServeArch, t: int, mm: TPUMachineModel,
                    axis_dims: Tuple[int, ...] = (),
                    cache=None, fingerprint: str = ""
                    ) -> Tuple[float, float]:
    """(decode_step_s, prefill_step_s) of one candidate, through the
    persistent cost cache when given: rows are stored OpCost-shaped
    (decode in fwd, prefill in bwd) under a key carrying the placement
    AND the full arch signature, inside a fingerprint carrying the
    serve dtypes — either flip misses."""
    key = None
    if cache is not None:
        key = cache.entry_key("serve_step", (t, tuple(axis_dims)),
                              extra=arch.signature())
        row = cache.get(fingerprint, key)
        if row is not None:
            return row.fwd, row.bwd
    dec = simulate_serve_step(arch, t, mm, axis_dims=axis_dims)
    pre = simulate_serve_step(arch, t, mm, axis_dims=axis_dims,
                              lanes=arch.prefill_lanes)
    if cache is not None:
        from .cost_model import OpCost
        cache.put(fingerprint, key,
                  OpCost(fwd=dec, bwd=pre, fwd_comm=0.0, bwd_comm=0.0,
                         sync=0.0, mem=0.0))
    return dec, pre


def optimize_serve(arch: ServeArch, num_devices: int, *,
                   mm: Optional[TPUMachineModel] = None,
                   config=None, budget: int = 64, alpha: float = 0.05,
                   seed: Optional[int] = None,
                   disaggregated: bool = False):
    """Pick the serve placement by simulated annealing over
    (degree, axis assignment) — the reference's Metropolis walk with
    the same relative-delta acceptance as mcmc._anneal — then return
    the best placement visited with its per-degree decode table.

    `config` (an FFConfig) supplies the machine model file, cost-cache
    path and seed the training search uses, so `--serve-mesh auto`
    prices serving on exactly the machine the training side was
    calibrated against. The space is small (divisor degrees × torus
    runs), so the default budget walks it to the optimum; the walk —
    not enumeration — is kept so richer placement spaces (replica
    counts, per-layer degrees) extend without restructuring.

    ``disaggregated=True`` searches the SPLIT serving space instead
    (prefill:decode engine ratio × per-role tensor degree, the page-
    handoff link priced on the host link) and returns a
    :class:`DisaggPlacement` — see :func:`optimize_serve_disagg`."""
    if disaggregated:
        return optimize_serve_disagg(arch, num_devices, mm=mm,
                                     config=config, seed=seed)
    if mm is None:
        from .machine_model import default_machine_model
        mm = default_machine_model(
            machine_file=getattr(config, "machine_model_file", None)
            if config is not None else None)
    if seed is None:
        seed = int(getattr(config, "seed", 0) or 0) \
            if config is not None else 0
    cache = None
    fingerprint = ""
    if config is None or getattr(config, "search_cost_cache", True):
        from .cost_cache import CostCache
        cache = CostCache.open(
            (getattr(config, "cost_cache_file", None) or None)
            if config is not None else None)
        fingerprint = _serve_fingerprint(mm, arch)

    degrees = candidate_degrees(arch, num_devices)
    space: List[Tuple[int, Tuple[int, ...]]] = [
        (t, dims) for t in degrees for dims in axis_assignments(mm, t)]

    def cost_of(cand) -> Tuple[float, float, float]:
        t, dims = cand
        dec, pre = price_placement(arch, t, mm, dims, cache=cache,
                                   fingerprint=fingerprint)
        return dec + PREFILL_WEIGHT * pre, dec, pre

    rng = random.Random(seed)
    walk_budget = max(len(space), int(budget))
    trace = None
    if config is None or getattr(config, "search_trace", True):
        from .trace import SearchTrace
        trace = SearchTrace(budget=walk_budget)
    cur = (1, ())
    cur_cost, cur_dec, cur_pre = cost_of(cur)
    best, best_cost = cur, cur_cost
    best_dec, best_pre = cur_dec, cur_pre
    if trace is not None:
        trace.record_best(-1, 0, best_cost)
    # every legal degree is priced once up front (flat ring) so the
    # returned per-degree table is complete — the paper's exhaustive
    # per-op config enumeration, affordable here because degrees are
    # few; the walk then also explores axis assignments
    decode_by_degree: Dict[int, float] = {}
    for t in degrees:
        c, dec, pre = cost_of((t, ()))
        decode_by_degree[t] = dec
        if c < best_cost:
            best, best_cost = (t, ()), c
            best_dec, best_pre = dec, pre
            if trace is not None:
                trace.record_best(-1, 0, best_cost)
    for it in range(walk_budget):
        nxt = space[rng.randrange(len(space))]
        if nxt == cur:
            continue
        nxt_cost, nxt_dec, nxt_pre = cost_of(nxt)
        t = nxt[0]
        if nxt_dec < decode_by_degree.get(t, float("inf")):
            decode_by_degree[t] = nxt_dec
        delta = nxt_cost - cur_cost
        temp = alpha * cur_cost
        accepted = delta <= 0 or rng.random() < math.exp(
            -delta / max(1e-12, temp))
        if accepted:
            cur, cur_cost = nxt, nxt_cost
            if cur_cost < best_cost:
                best, best_cost = cur, cur_cost
                best_dec, best_pre = nxt_dec, nxt_pre
                if trace is not None:
                    trace.record_best(it, 0, best_cost)
        if trace is not None:  # observation only, after the decision —
            # traced and untraced walks consume the RNG identically
            trace.record(it, 0, "serve_place",
                         f"t={t} dims={tuple(nxt[1])}", delta,
                         accepted, temp, "serve")
    if cache is not None:
        cache.flush()
    return ServePlacement(
        tensor_parallel=best[0], axis_dims=tuple(best[1]),
        decode_step_s=best_dec, prefill_step_s=best_pre,
        cost=best_cost, decode_by_degree=dict(
            sorted(decode_by_degree.items())),
        fingerprint=fingerprint,
        trace=trace.summary() if trace is not None else None)


# ---------------------------------------------------------------------------
# 2-D (tensor x data) serve mesh placement — docs/search.md "2-D serve mesh"
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MeshTraffic:
    """The traffic model the 2-D mesh objective prices a pool against:
    an aggregate arrival rate split across the replica count, a
    prefix-affinity hit rate over shared preambles (discounted as
    replicas multiply — each replica's cache must see a preamble once
    before it hits), and the SLO targets that turn throughput into
    goodput. Every field folds into the mesh cost-cache fingerprint
    (:func:`_mesh_fingerprint`), so an SLO or rate flip is a
    guaranteed cache miss."""
    arrival_rps: float = 8.0
    # fraction of a steady-state prompt's tokens served from the
    # prefix cache when ONE replica has seen the preamble
    prefix_hit: float = 0.0
    # how many requests share each preamble (tenant fan-in): the
    # hit-rate discount spreads each preamble's one-per-replica cold
    # prefill over this many requests
    requests_per_preamble: float = 8.0
    slo_ttft_s: float = 0.0     # 0 = unbounded
    slo_tpot_s: float = 0.0

    @classmethod
    def from_config(cls, config=None, **over) -> "MeshTraffic":
        """SLO targets from FFConfig's --slo-ttft-ms/--slo-tpot-ms;
        any field overridable by keyword."""
        kw = {}
        if config is not None:
            tt = float(getattr(config, "slo_ttft_ms", 0.0) or 0.0)
            tp = float(getattr(config, "slo_tpot_ms", 0.0) or 0.0)
            if tt:
                kw["slo_ttft_s"] = tt / 1e3
            if tp:
                kw["slo_tpot_s"] = tp / 1e3
        kw.update(over)
        return cls(**kw)

    def signature(self) -> Tuple:
        return ("mesh_v1", float(self.arrival_rps),
                float(self.prefix_hit),
                float(self.requests_per_preamble),
                float(self.slo_ttft_s), float(self.slo_tpot_s))


def _mesh_fingerprint(mm: TPUMachineModel, arch: ServeArch,
                      traffic: MeshTraffic) -> str:
    """The 1-D serve fingerprint widened with the traffic/SLO tuple:
    mesh rows can never resurrect across a kv-dtype, adapter-geometry,
    arrival-rate or SLO-target flip (the acceptance-criteria miss
    guarantee — step prices don't depend on the SLO, but pricing them
    under the wider scope trades a few re-simulations for a fingerprint
    a test can audit field by field)."""
    from .cost_cache import machine_fingerprint
    return machine_fingerprint(
        mm, serve=_serve_signature(arch) + traffic.signature())


@dataclasses.dataclass(frozen=True)
class ServeMeshPlacement:
    """One 2-D (tensor x data) pool placement the mesh search priced
    (the winner when returned by optimize_serve_mesh): shard the mixed
    program ``tensor_parallel`` ways, run ``replicas`` data-parallel
    copies of it (t*r <= the device budget), each axis riding the
    recorded torus dims (() = flat ring). ``table`` is the full priced
    (t, r) grid — what the autoscaler's target pricing and the
    chosen-vs-rejected explain render read — and ``infeasible`` the
    degrees whose per-device residency (serve_device_bytes: weight
    shard + KV pool + adapter pool) overflows HBM: rejected before
    pricing, never penalty-priced."""
    tensor_parallel: int
    replicas: int
    tensor_axis_dims: Tuple[int, ...]
    data_axis_dims: Tuple[int, ...]
    decode_step_s: float
    prefill_step_s: float
    mixed_step_s: float
    goodput_per_s: float
    cost: float
    num_devices: int = 0
    # (t, r) -> cell metrics dict (goodput_per_s, capacity_rps,
    # tokens_per_s, tpot_s, ttft_s, decode/prefill/mixed_step_s,
    # slo_ok, device_bytes) for every FEASIBLE cell
    table: Dict[Tuple[int, int], dict] = dataclasses.field(
        default_factory=dict)
    # HBM-rejected degrees: {"tensor", "device_bytes", "hbm_capacity",
    # "reason"} — one entry per rejected t (every r shares the verdict)
    infeasible: Tuple[dict, ...] = ()
    # per-degree decode step at the flat ring (feasible degrees only):
    # the 1-D table shape the autoscaler's fallback pricing reads
    decode_by_degree: Dict[int, float] = dataclasses.field(
        default_factory=dict)
    traffic: Optional[dict] = None
    fingerprint: str = ""
    trace: Optional[dict] = None

    def cell(self, t: int, r: int) -> Optional[dict]:
        return self.table.get((int(t), int(r)))

    def _best_goodput(self, pred) -> float:
        vals = [c["goodput_per_s"] for k, c in self.table.items()
                if pred(k)]
        return max(vals) if vals else 0.0

    def goodput_gain_vs_tensor_only(self) -> float:
        """Chosen cell's goodput over the best r=1 (pure tensor)
        column — one of the two degenerate baselines the bench gates."""
        base = self._best_goodput(lambda k: k[1] == 1)
        return self.goodput_per_s / max(base, 1e-12)

    def goodput_gain_vs_replicas_only(self) -> float:
        """Chosen cell's goodput over the best t=1 (pure replicas)
        row; infinite when t=1 never fit HBM (the rejection IS the
        win)."""
        base = self._best_goodput(lambda k: k[0] == 1)
        return self.goodput_per_s / max(base, 1e-12)


def price_mesh_step(arch: ServeArch, t: int, mm: TPUMachineModel,
                    axis_dims: Tuple[int, ...] = (), cache=None,
                    fingerprint: str = ""
                    ) -> Tuple[float, float, float]:
    """(decode_step_s, prefill_step_s, mixed_step_s) of one tensor
    degree, through the persistent cost cache when given — the mesh
    search's step-price row (the 1-D row plus the mixed-width step the
    pool's TPOT actually runs at), stored under the WIDENED mesh
    fingerprint + the full arch signature."""
    key = None
    if cache is not None:
        key = cache.entry_key("serve_mesh_step", (t, tuple(axis_dims)),
                              extra=arch.signature())
        row = cache.get(fingerprint, key)
        if row is not None:
            return row.fwd, row.bwd, row.fwd_comm
    dec = simulate_serve_step(arch, t, mm, axis_dims=axis_dims)
    pre = simulate_serve_step(arch, t, mm, axis_dims=axis_dims,
                              lanes=arch.prefill_lanes)
    mixed = simulate_serve_step(
        arch, t, mm, axis_dims=axis_dims,
        lanes=arch.decode_lanes + arch.prefill_lanes)
    if cache is not None:
        from .cost_model import OpCost
        cache.put(fingerprint, key,
                  OpCost(fwd=dec, bwd=pre, fwd_comm=mixed,
                         bwd_comm=0.0, sync=0.0, mem=0.0))
    return dec, pre, mixed


def mesh_cell_metrics(arch: ServeArch, t: int, r: int, dec: float,
                      pre: float, mixed: float,
                      traffic: MeshTraffic) -> dict:
    """The pool-level objective of one feasible (t, r) cell: compose
    the per-replica step prices with the traffic model into
    goodput-under-SLO.

    Steady state: each request decodes ``decode_tokens`` tokens on a
    lane of the mixed-width step (TPOT = the mixed step — decode lanes
    pay for the prefill budget riding along) and prefills the NON-hit
    fraction of its context in budget-sized chunks. The prefix-hit
    discount shrinks with r (each replica's cache must ingest a
    preamble once, amortized over the requests sharing it), which is
    exactly the force pulling AGAINST replicas that the 2-D search
    trades off. Capacity is r requests in flight per per-request
    seconds; TTFT is the prefill time inflated by 1/(1-rho) queueing
    as utilization approaches saturation; goodput is arrival capped by
    capacity, zeroed when either SLO target (when set) is violated."""
    dtok = max(1, int(getattr(arch, "decode_tokens", 64)))
    h = float(traffic.prefix_hit) * max(
        0.0, 1.0 - (r - 1.0) / max(1.0, traffic.requests_per_preamble))
    h = min(1.0, max(0.0, h))
    fresh_tokens = arch.context * (1.0 - h)
    chunks = max(1, math.ceil(fresh_tokens / max(1, arch.prefill_lanes)))
    per_request_s = (mixed * dtok / max(1, arch.decode_lanes)
                     + pre * chunks)
    capacity_rps = r / max(1e-12, per_request_s)
    rho = min(0.999, traffic.arrival_rps / max(1e-12, capacity_rps))
    tpot_s = mixed
    ttft_s = pre * chunks / (1.0 - rho)
    slo_ok = not ((traffic.slo_tpot_s and tpot_s > traffic.slo_tpot_s)
                  or (traffic.slo_ttft_s
                      and ttft_s > traffic.slo_ttft_s))
    goodput = min(traffic.arrival_rps, capacity_rps) if slo_ok else 0.0
    return {
        "tensor": t, "replicas": r,
        "goodput_per_s": goodput,
        "capacity_rps": capacity_rps,
        # pool decode-token throughput ceiling — what the autoscaler's
        # demand gauge (decode tokens/s) compares against
        "tokens_per_s": r * arch.decode_lanes / max(1e-12, mixed),
        "tpot_s": tpot_s, "ttft_s": ttft_s,
        "prefix_hit_effective": h,
        "prefill_chunks": chunks,
        "decode_step_s": dec, "prefill_step_s": pre,
        "mixed_step_s": mixed,
        "slo_ok": bool(slo_ok),
    }


def optimize_serve_mesh(arch: ServeArch, num_devices: int, *,
                        mm: Optional[TPUMachineModel] = None,
                        config=None,
                        traffic: Optional[MeshTraffic] = None,
                        budget: int = 96, alpha: float = 0.05,
                        seed: Optional[int] = None,
                        fixed_tensor: Optional[int] = None,
                        fixed_replicas: Optional[int] = None
                        ) -> ServeMeshPlacement:
    """The paper's ONE-search discipline applied to the serving pool:
    a single Metropolis walk over 2-D (tensor degree x replica count)
    placements with a torus-axis assignment for each axis, t*r bounded
    by the device budget, priced by the pool-level goodput-under-SLO
    objective (:func:`mesh_cell_metrics`). Degrees whose per-device
    residency overflows HBM are REJECTED up front (never proposed,
    never penalty-priced) — the feasibility frontier is part of the
    answer, recorded in ``infeasible``.

    Every feasible (t, r) is priced once at the flat ring first so the
    returned table is complete (the exhaustive half, affordable
    because the grid is divisors x counts); the walk then explores
    axis assignments under the same accept rule as ``optimize_serve``.
    ``fixed_tensor``/``fixed_replicas`` pin one dimension (an explicit
    --serve-mesh N beside --serve-replicas auto, or vice versa).
    Step prices persist in the shared CostCache under the widened
    :func:`_mesh_fingerprint`."""
    if mm is None:
        from .machine_model import default_machine_model
        mm = default_machine_model(
            machine_file=getattr(config, "machine_model_file", None)
            if config is not None else None)
    if traffic is None:
        traffic = MeshTraffic.from_config(config)
    if seed is None:
        seed = int(getattr(config, "seed", 0) or 0) \
            if config is not None else 0
    n = max(1, int(num_devices))
    cache = None
    fingerprint = ""
    if config is None or getattr(config, "search_cost_cache", True):
        from .cost_cache import CostCache
        cache = CostCache.open(
            (getattr(config, "cost_cache_file", None) or None)
            if config is not None else None)
        fingerprint = _mesh_fingerprint(mm, arch, traffic)

    degrees = candidate_degrees(arch, n)
    if fixed_tensor is not None:
        t0 = int(fixed_tensor)
        if t0 not in degrees:
            raise ValueError(
                f"fixed tensor degree {t0} is not a feasible degree "
                f"for {arch.num_heads} heads on {n} devices")
        degrees = [t0]
    hbm = float(getattr(mm.spec, "hbm_capacity", float("inf")))
    infeasible: List[dict] = []
    feasible: List[int] = []
    for t in degrees:
        b = serve_device_bytes(arch, t)
        if b > hbm:
            infeasible.append({
                "tensor": t, "device_bytes": b, "hbm_capacity": hbm,
                "reason": f"per-device residency "
                          f"{b / 2**20:.1f} MiB > HBM "
                          f"{hbm / 2**20:.1f} MiB"})
        else:
            feasible.append(t)
    if not feasible:
        raise ValueError(
            f"no tensor degree fits HBM on this machine "
            f"({[d['reason'] for d in infeasible]})")

    def replica_counts(t: int) -> List[int]:
        top = n // t
        if fixed_replicas is not None:
            rr = int(fixed_replicas)
            return [rr] if 1 <= rr <= top else []
        return list(range(1, top + 1))

    step_cache: Dict[Tuple[int, Tuple[int, ...]], Tuple[float, float,
                                                        float]] = {}

    def steps_of(t: int, dims: Tuple[int, ...]):
        k = (t, tuple(dims))
        if k not in step_cache:
            step_cache[k] = price_mesh_step(
                arch, t, mm, dims, cache=cache, fingerprint=fingerprint)
        return step_cache[k]

    def cost_of(cand) -> Tuple[float, dict]:
        t, r, tdims, _ddims = cand
        dec, pre, mixed = steps_of(t, tdims)
        cell = mesh_cell_metrics(arch, t, r, dec, pre, mixed, traffic)
        # goodput carries the objective; TPOT then TTFT break ties
        # between cells that both sustain the arrival rate (prefer the
        # lower-latency shape), and a vanishing device-count term makes
        # equal-everything ties deterministic
        cost = (-cell["goodput_per_s"] + cell["tpot_s"]
                + 1e-3 * cell["ttft_s"] + 1e-9 * t * r)
        return cost, cell

    # exhaustive flat-ring pricing of the full feasible grid: the
    # returned table must be complete even where the walk never lands
    table: Dict[Tuple[int, int], dict] = {}
    decode_by_degree: Dict[int, float] = {}
    best = None
    best_cost = float("inf")
    best_cell: Optional[dict] = None
    for t in feasible:
        for r in replica_counts(t):
            c, cell = cost_of((t, r, (), ()))
            table[(t, r)] = cell
            decode_by_degree[t] = cell["decode_step_s"]
            if c < best_cost:
                best, best_cost, best_cell = (t, r, (), ()), c, cell
    if best is None:
        raise ValueError(
            f"no (t, r) cell fits {n} devices with "
            f"fixed_tensor={fixed_tensor} "
            f"fixed_replicas={fixed_replicas}")

    space: List[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]] = [
        (t, r, tdims, ddims)
        for t in feasible for r in replica_counts(t)
        for tdims in axis_assignments(mm, t)
        for ddims in axis_assignments(mm, r)]
    rng = random.Random(seed)
    walk_budget = max(len(space), int(budget))
    trace = None
    if config is None or getattr(config, "search_trace", True):
        from .trace import SearchTrace
        trace = SearchTrace(budget=walk_budget)
        trace.record_best(-1, 0, best_cost)
    cur, cur_cost = best, best_cost
    for it in range(walk_budget):
        nxt = space[rng.randrange(len(space))]
        if nxt == cur:
            continue
        nxt_cost, nxt_cell = cost_of(nxt)
        cell_key = (nxt[0], nxt[1])
        if nxt_cell["goodput_per_s"] >= table[cell_key][
                "goodput_per_s"] and nxt[2] != ():
            # a torus-assigned step that beats the flat ring upgrades
            # the table's cell (the table records each cell's BEST)
            if nxt_cost < cost_of((nxt[0], nxt[1], (), ()))[0]:
                table[cell_key] = nxt_cell
        delta = nxt_cost - cur_cost
        temp = alpha * max(1e-12, abs(cur_cost))
        accepted = delta <= 0 or rng.random() < math.exp(
            -delta / max(1e-12, temp))
        if accepted:
            cur, cur_cost = nxt, nxt_cost
            if cur_cost < best_cost:
                best, best_cost, best_cell = cur, cur_cost, nxt_cell
                if trace is not None:
                    trace.record_best(it, 0, best_cost)
        if trace is not None:  # observation only, after the decision —
            # traced and untraced walks consume the RNG identically
            trace.record(it, 0, "serve_mesh",
                         f"t={nxt[0]} r={nxt[1]} "
                         f"tdims={tuple(nxt[2])} "
                         f"ddims={tuple(nxt[3])}", delta,
                         accepted, temp, "serve")
    if cache is not None:
        cache.flush()
    t, r, tdims, ddims = best
    return ServeMeshPlacement(
        tensor_parallel=t, replicas=r,
        tensor_axis_dims=tuple(tdims), data_axis_dims=tuple(ddims),
        decode_step_s=best_cell["decode_step_s"],
        prefill_step_s=best_cell["prefill_step_s"],
        mixed_step_s=best_cell["mixed_step_s"],
        goodput_per_s=best_cell["goodput_per_s"],
        cost=best_cost, num_devices=n,
        table=dict(sorted(table.items())),
        infeasible=tuple(infeasible),
        decode_by_degree=dict(sorted(decode_by_degree.items())),
        traffic=dict(zip(("version", "arrival_rps", "prefix_hit",
                          "requests_per_preamble", "slo_ttft_s",
                          "slo_tpot_s"), traffic.signature())),
        fingerprint=fingerprint,
        trace=trace.summary() if trace is not None else None)


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode placement (serve/disagg.py's search half)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DisaggPlacement:
    """One disaggregated serving placement the search priced: how many
    dedicated prefill vs decode engines to run (at which per-role
    tensor degrees), with the page-handoff link costed on the host
    link. ``ratio_table`` maps "p:d" engine ratios to their best
    steady-state per-request seconds (per-role degrees optimized away)
    — the disaggregated mirror of ServePlacement.decode_by_degree."""

    prefill_engines: int
    prefill_tensor: int
    decode_engines: int
    decode_tensor: int
    # steady-state components of the winning candidate (seconds)
    decode_step_s: float        # one decode-engine step — the TPOT floor
    prefill_step_s: float       # one budget-wide prefill-engine step
    transfer_s: float           # one request's page handoff on the link
    bottleneck_s: float         # slowest pipeline stage, per request
    cost: float
    # "p:d" -> best per-request seconds at that engine ratio
    ratio_table: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # the unified baseline at the same device count (optimize_serve's
    # winner run as num_devices/t data-parallel replicas): its TPOT is
    # the full mixed-width step — what the A/B's reduction is against
    unified_tpot_s: float = 0.0
    unified_per_request_s: float = 0.0
    fingerprint: str = ""

    @property
    def ratio(self) -> str:
        return f"{self.prefill_engines}:{self.decode_engines}"

    def tpot_reduction_vs_unified(self) -> float:
        """Simulated TPOT win of the split: the unified engine's
        mixed-width step over the decode engine's decode-only step.
        Degrades to 1.0 with a warning when the unified baseline was
        never priced (a partial-budget search)."""
        if not self.unified_tpot_s:
            warnings.warn(
                "disagg placement has no unified-baseline TPOT; "
                "reporting tpot_reduction_vs_unified as 1.0x",
                RuntimeWarning, stacklevel=2)
            return 1.0
        if not self.decode_step_s:
            return 1.0
        return self.unified_tpot_s / self.decode_step_s


def price_disagg_candidate(arch: ServeArch, t_pre: int, t_dec: int,
                           mm: TPUMachineModel, *, cache=None,
                           fingerprint: str = ""
                           ) -> Tuple[float, float, float]:
    """(prefill_step_s, decode_step_s, transfer_s) of one per-role
    degree pair, through the persistent cost cache when given.

    The prefill engine's step is the budget-wide mixed program at
    ``t_pre``; the decode engine's step is its REAL fixed program —
    ``decode_lanes`` query lanes plus the ``handoff_stub_lanes``
    prefill stub that recomputes handoff tails (no full prefill
    budget riding along, the whole point of the split) — at
    ``t_dec``, priced WITH the
    steady-state page-handoff load importing beside it
    (cost_model.serve_step_tasks): the decode engine turns over its
    ``decode_lanes`` requests every ``decode_tokens`` steps, so each
    step imports ``context * decode_lanes / decode_tokens`` tokens'
    pages on average; the transfer term itself is the host-link
    seconds of one full context's pages — what the ratio balance
    weighs against freed compute. Cached rows carry the full arch
    signature (kv dtype/itemsize included), so a KV-dtype flip is a
    guaranteed miss AND a changed transfer price."""
    key = None
    if cache is not None:
        key = cache.entry_key("serve_disagg", (t_pre, t_dec),
                              extra=arch.signature())
        row = cache.get(fingerprint, key)
        if row is not None:
            return row.fwd, row.bwd, row.sync
    pre = simulate_serve_step(arch, t_pre, mm,
                              lanes=arch.prefill_lanes)
    per_step_tokens = max(1, round(
        arch.context * arch.decode_lanes
        / max(1, getattr(arch, "decode_tokens", 64))))
    dec_lanes = arch.decode_lanes + int(
        getattr(arch, "handoff_stub_lanes", 32))
    dec = simulate_serve_step(arch, t_dec, mm, lanes=dec_lanes,
                              transfer_tokens=per_step_tokens)
    xfer = mm.host_transfer(kv_handoff_bytes(arch))
    if cache is not None:
        from .cost_model import OpCost
        cache.put(fingerprint, key,
                  OpCost(fwd=pre, bwd=dec, fwd_comm=0.0, bwd_comm=0.0,
                         sync=xfer, mem=0.0))
    return pre, dec, xfer


def optimize_serve_disagg(arch: ServeArch, num_devices: int, *,
                          mm: Optional[TPUMachineModel] = None,
                          config=None,
                          seed: Optional[int] = None
                          ) -> DisaggPlacement:
    """Pick the prefill:decode split — engine counts × per-role tensor
    degrees — whose steady-state per-request bottleneck is smallest:
    the SOAP don't-hand-tune-it discipline applied to the
    disaggregation axis (ROADMAP).

    Steady state under mixed traffic: every request prefills its
    ``context`` tokens in budget-sized chunks on SOME prefill engine,
    ships its pages over the host link once, and decodes
    ``decode_tokens`` tokens on a decode-lane of SOME decode engine.
    Each stage's per-request seconds:

      prefill  = prefill_step_s * ceil(context/prefill_lanes) / p
      transfer = host_transfer(kv_handoff_bytes) / p   (one DMA link
                 per prefill engine's host)
      decode   = decode_step_s * decode_tokens / decode_lanes / d

    and the pipeline sustains 1/max(stages) requests per second. The
    objective is that bottleneck plus ``PREFILL_WEIGHT`` × the decode
    step (TTFT already carries the prefill weight in the unified
    objective; here the extra term keeps a ratio that wrecks TPOT from
    winning on raw throughput). The space is small (ratios × divisor
    degrees), so it is enumerated exhaustively — the per-op
    exhaustive-config half of the reference search — and the full
    ratio table is returned the way optimize_serve returns the
    per-degree decode table."""
    if mm is None:
        from .machine_model import default_machine_model
        mm = default_machine_model(
            machine_file=getattr(config, "machine_model_file", None)
            if config is not None else None)
    n = max(2, int(num_devices))
    cache = None
    fingerprint = ""
    if config is None or getattr(config, "search_cost_cache", True):
        from .cost_cache import CostCache
        cache = CostCache.open(
            (getattr(config, "cost_cache_file", None) or None)
            if config is not None else None)
        fingerprint = _serve_fingerprint(mm, arch)

    degrees = candidate_degrees(arch, n)
    chunks_per_prompt = max(1.0, math.ceil(
        arch.context / max(1, arch.prefill_lanes)))
    dec_tokens = max(1, int(getattr(arch, "decode_tokens", 64)))

    best = None
    best_cost = float("inf")
    ratio_table: Dict[str, float] = {}
    # each role's step cost depends on ITS degree only (the transfer
    # term on neither), so one pricing per degree covers every
    # (t_pre, t_dec) pair — O(D) simulations, not O(D^2)
    priced = {t: price_disagg_candidate(arch, t, t, mm, cache=cache,
                                        fingerprint=fingerprint)
              for t in degrees}
    for t_pre in degrees:
        pre = priced[t_pre][0]
        for t_dec in degrees:
            dec, xfer = priced[t_dec][1], priced[t_dec][2]
            p_max = (n - t_dec) // t_pre
            if p_max < 1:
                continue
            for p in range(1, p_max + 1):
                d = (n - p * t_pre) // t_dec
                if d < 1:
                    continue
                stage_pre = pre * chunks_per_prompt / p
                stage_xfer = xfer / p
                stage_dec = dec * dec_tokens / max(
                    1, arch.decode_lanes) / d
                bottleneck = max(stage_pre, stage_xfer, stage_dec)
                cost = bottleneck + PREFILL_WEIGHT * dec
                ratio = f"{p}:{d}"
                if bottleneck < ratio_table.get(ratio, float("inf")):
                    ratio_table[ratio] = bottleneck
                if cost < best_cost:
                    best_cost = cost
                    best = (p, t_pre, d, t_dec, pre, dec, xfer,
                            bottleneck)
    if best is None:
        raise ValueError(
            f"no disaggregated placement fits {num_devices} devices "
            f"(need >= 1 prefill + 1 decode engine)")

    # the unified baseline at the same device count: optimize_serve's
    # winner replicated data-parallel, its TPOT the FULL mixed-width
    # step (decode lanes pay for the prefill budget every step — the
    # interference disaggregation removes)
    uni = optimize_serve(arch, n, mm=mm, config=config, seed=seed)
    replicas = max(1, n // max(1, uni.tensor_parallel))
    uni_tpot = simulate_serve_step(
        arch, uni.tensor_parallel, mm, axis_dims=uni.axis_dims,
        lanes=arch.decode_lanes + arch.prefill_lanes)
    uni_per_req = (uni_tpot * dec_tokens / max(1, arch.decode_lanes)
                   + uni.prefill_step_s * chunks_per_prompt) / replicas

    if cache is not None:
        cache.flush()
    p, t_pre, d, t_dec, pre, dec, xfer, bottleneck = best

    def _ratio_key(r: str) -> Tuple[int, int]:
        a, b = r.split(":")
        return int(a), int(b)

    return DisaggPlacement(
        prefill_engines=p, prefill_tensor=t_pre,
        decode_engines=d, decode_tensor=t_dec,
        decode_step_s=dec, prefill_step_s=pre, transfer_s=xfer,
        bottleneck_s=bottleneck, cost=best_cost,
        ratio_table=dict(sorted(ratio_table.items(),
                                key=lambda kv: _ratio_key(kv[0]))),
        unified_tpot_s=uni_tpot, unified_per_request_s=uni_per_req,
        fingerprint=fingerprint)
