"""Native-backed MCMC strategy search.

Lowers the model graph + per-op candidate strategies into flat arrays
and runs the annealing loop in C++ (csrc/mcmc.cc) — the native hot loop
the reference keeps in FFModel::optimize + Simulator::simulate_runtime
(model.cc:1905-1968, simulator.cc:330-629).  Candidate costs still come
from the Python cost model (cost_model.op_cost), computed once per
(op, candidate) up front; only the search walk itself is native.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import threading

from ..parallel.pconfig import OpStrategy, Strategy
from .simulator import Simulator, _axis_sig, op_edges

# the C++ engine predates the threaded mesh-shape sweep; serialize
# entry rather than audit csrc/mcmc.cc for hidden global state (the
# native walk is fast — Python-side annealing still overlaps it)
_NATIVE_LOCK = threading.Lock()


def _map_key(m: Dict[str, object]):
    return tuple(sorted((k, str(v)) for k, v in m.items()))


def lower_to_arrays(model, sim: Simulator, cands: Dict[str, list],
                    init_strategy: Strategy):
    """Build (CostTable, edges, prop_match, init assignment, cand lists).

    Edge order matches the Python simulator's iteration over op.inputs
    so backward-dependency construction is identical in both engines."""
    from ..native.wrappers import CostTable

    ops = model.ops
    op_index = {op.name: i for i, op in enumerate(ops)}

    cand_lists: List[List[dict]] = []
    for op in ops:
        lst = [dict(m) for m in cands[op.name]]
        init_map = dict(init_strategy.for_op(op.name).axis_map)
        if _map_key(init_map) not in {_map_key(m) for m in lst}:
            lst.append(init_map)  # searchable back to candidates either way
        cand_lists.append(lst)

    init_assign = []
    for i, op in enumerate(ops):
        init_map = _map_key(dict(init_strategy.for_op(op.name).axis_map))
        idx = next(j for j, m in enumerate(cand_lists[i])
                   if _map_key(m) == init_map)
        init_assign.append(idx)

    table = CostTable([len(l) for l in cand_lists],
                      n_devices=int(sim.mesh.size))
    for i, op in enumerate(ops):
        for j, m in enumerate(cand_lists[i]):
            s = OpStrategy(dict(m))
            # priced through the simulator's 3-tier cost cache (memory
            # -> persistent disk store -> compute, with measured
            # grounding applied at compute) — both engines rank on the
            # same numbers, and the native table, the biggest per-search
            # cost consumer (ops x candidates), populates and reuses
            # the fingerprint-keyed persistent store too
            c = sim._op_cost_for(op, s, _axis_sig(s))
            table.set(i, j, c, devices=s.device_ids)

    _, op_pairs = op_edges(model)
    edges: List[Tuple[int, int]] = [
        (op_index[src.name], op_index[dst.name]) for src, dst in op_pairs]

    prop_match = []
    for src, dst in edges:
        keys_dst = {_map_key(m): j for j, m in enumerate(cand_lists[dst])}
        prop_match.append([keys_dst.get(_map_key(m), -1)
                           for m in cand_lists[src]])

    return table, edges, prop_match, init_assign, cand_lists


def optimize_native(model, sim: Simulator, cands: Dict[str, list],
                    budget: int, alpha: float, seed: int,
                    verbose: bool = False) -> Optional[Strategy]:
    """Run the search natively; None if the native library is missing."""
    from .. import native
    if not native.available():
        return None
    from ..native.wrappers import mcmc_search

    cfg = model.config
    init = (model.strategy or Strategy()).copy()
    with _NATIVE_LOCK:
        table, edges, prop_match, init_assign, cand_lists = \
            lower_to_arrays(model, sim, cands, init)
        best_idx, best_cost = mcmc_search(
            table, edges, prop_match, budget, alpha, seed,
            enable_propagation=bool(cfg.enable_propagation),
            overlap_backward_sync=sim.overlap,
            hbm_capacity=sim.mm.spec.hbm_capacity,
            time_scale=sim.time_scale,
            init_cand=init_assign,
            step_overhead=sim.step_overhead)

    best = init.copy()
    for i, op in enumerate(model.ops):
        best.set(op.name, OpStrategy(dict(cand_lists[i][int(best_idx[i])])))
    if verbose:
        print(f"[search/native] best estimated step time: "
              f"{best_cost*1e3:.3f} ms")
    return best
