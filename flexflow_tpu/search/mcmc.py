"""MCMC strategy search (reference: FFModel::optimize, model.cc:1905-1968).

Round-1 placeholder: returns the data-parallel default so
compile(search_budget>0) is functional; the annealing loop over the
simulator lands with the cost-model milestone.
"""

from __future__ import annotations

import warnings

from ..parallel.pconfig import Strategy


def optimize(model, budget: int = 0, alpha: float = 0.05) -> Strategy:
    warnings.warn("MCMC strategy search not yet implemented; "
                  "returning data-parallel default strategy")
    return model.strategy or Strategy()
