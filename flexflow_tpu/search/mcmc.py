"""MCMC strategy search.

Direct analog of the reference `FFModel::optimize` (model.cc:1905-1968):
simulated annealing over per-op strategies, starting from pure data
parallelism, with two move types — `rewrite` (re-strategize one random op)
and, with probability 0.25, `propagate` (copy an op's strategy to a graph
neighbor; reference model.cc:1807-1903) — accepting uphill moves with
probability exp(-alpha * delta), and resetting to the best strategy every
budget/100 iterations.

The candidate set per op is the TPU-native strategy space: which logical
axes map to which mesh axes, gated by the same CLI flags the reference
used (--enable-parameter-parallel etc., config.h:139-141) plus the new
SP/EP/PP axes.
"""

from __future__ import annotations

import math
import os
import random
import time
from typing import Dict, List, Optional

from ..parallel.pconfig import DEVICE_KEY, OpStrategy, Strategy
from .measure import calibrated_machine_model
from .simulator import Simulator, op_edges


def _resolve_chains(cfg, chains: Optional[int]) -> int:
    """Number of parallel annealing chains: explicit arg >
    FFConfig.search_chains > min(4, cpu_count)."""
    if chains is None:
        chains = int(getattr(cfg, "search_chains", 0) or 0)
    if chains <= 0:
        chains = min(4, os.cpu_count() or 1)
    return max(1, chains)


def _chain_seed(seed: int, k: int) -> int:
    """Per-chain RNG seed derived from cfg.seed; chain 0 reproduces the
    single-chain walk for the same base seed."""
    return seed + 7919 * k


def candidate_maps(op, mesh, cfg, op_index: int = 0) -> List[Dict[str, str]]:
    """Enumerate legal axis maps for one op on this mesh.

    `op_index` seeds the round-robin device for device-explicit placement
    candidates (the reference's DLRM strategy generator assigns table i
    to GPU i % n, dlrm_strategy.py)."""
    axes = mesh.shape
    cands: List[Dict[str, str]] = []
    base: Dict[str, str] = {}
    if "data" in axes and cfg.enable_sample_parallel:
        base = {"sample": "data"}
    cands.append(dict(base))          # pure DP (or replicated)
    if not base:
        cands.append({})

    model_ax = "model" if "model" in axes else None
    if model_ax:
        tp_ok = cfg.enable_parameter_parallel or cfg.enable_attribute_parallel
        if tp_ok and op.op_type in ("linear", "lstm"):
            cands.append({**base, "channel_out": model_ax})
        if cfg.enable_attribute_parallel and op.op_type == "conv2d":
            cands.append({**base, "channel_out": model_ax})
        if tp_ok and op.op_type == "multihead_attention":
            cands.append({**base, "head": model_ax})
        if cfg.enable_parameter_parallel and op.op_type == "embedding":
            cands.append({**base, "vocab": model_ax})
        if cfg.enable_parameter_parallel \
                and op.op_type == "distributed_embedding":
            cands.append({**base, "vocab": model_ax})
            cands.append({**base, "table": model_ax})

    # device-explicit placement ("Operator"/"Parameter" dims of SOAP:
    # reference ParallelConfig.device_ids, config.h:47-73) — pin the
    # whole op to one device, round-robin by op index like the DLRM
    # strategy generator. OPT-IN (--enable-device-placement): GSPMD
    # executes these as replication, so by default the search only
    # offers executable candidates (table sharding on
    # distributed_embedding is the executable placement form).
    n_dev = int(mesh.size) if hasattr(mesh, "size") else 1
    if (getattr(cfg, "enable_device_placement", False)
            and op.op_type == "embedding" and n_dev > 1):
        cands.append({DEVICE_KEY: (op_index % n_dev,)})
    if (getattr(cfg, "enable_device_placement", False)
            and op.op_type == "distributed_embedding" and n_dev > 1):
        # per-table explicit ids (the DLRM strategy-generator pattern,
        # dlrm_strategy.cc:1-50) — EXECUTABLE via the op's slot layout:
        # round-robin and blocked assignments (shared with
        # tools/gen_dlrm_strategy.py via placement_assignment)
        from ..parallel.pconfig import placement_assignment
        ntab = getattr(op, "num_tables", 1)
        cands.append({DEVICE_KEY: placement_assignment(
            ntab, n_dev, "round_robin")})
        if ntab >= n_dev:
            cands.append({DEVICE_KEY: placement_assignment(
                ntab, n_dev, "blocked")})

    if cfg.enable_sequence_parallel and "seq" in axes:
        if op.op_type in ("multihead_attention", "linear", "lstm",
                          "element_unary", "element_binary", "dropout",
                          "softmax", "moe_ffn"):
            cands.append({**base, "seq": "seq"})
            if model_ax and op.op_type == "multihead_attention":
                cands.append({**base, "seq": "seq", "head": model_ax})

    if cfg.enable_expert_parallel and op.op_type == "moe_ffn":
        ep_ax = "expert" if "expert" in axes else model_ax
        if ep_ax:
            cands.append({**base, "expert": ep_ax})

    if cfg.enable_pipeline_parallel and op.op_type == "pipeline_blocks":
        if "pipe" in axes:
            cands.append({**base, "layer": "pipe"})

    # dedupe
    seen = set()
    out = []
    for c in cands:
        key = tuple(sorted(c.items()))
        if key not in seen:
            seen.add(key)
            out.append(c)
    return out


def _pipe_candidate_sizes(mesh) -> List[int]:
    """Non-data mesh-axis sizes a pipeline could ride — the shared
    enumeration for v=1 staged candidates and the v>1 sweep."""
    return sorted({size for name, size in mesh.shape.items()
                   if name != "data" and size > 1})


def _pin_free_strategy(mesh) -> Strategy:
    """The data-default strategy staged candidates build on."""
    return Strategy(default=OpStrategy({"sample": "data"}
                                       if "data" in mesh.shape else {}))


def staged_strategies(model, mesh, cfg) -> List[Strategy]:
    """Whole-graph pipeline candidates: flops-balanced stage cuts
    expressed as per-op whole-device pins (the executable graph-PP form,
    core/staged.py) — one candidate per viable non-data mesh-axis size.
    These are GLOBAL moves (a single op's pin is useless alone; the
    reference's propagate move spread placements the same way,
    model.cc:1807-1903)."""
    if not getattr(cfg, "enable_pipeline_parallel", False):
        return []
    if any(op.op_type == "pipeline_blocks" for op in model.ops):
        # the uniform-stack meta-op already owns the pipe axis (and
        # the native engine prices it); don't nest graph-level stages
        return []
    from ..parallel.graph_pipeline import (
        balanced_stages, build_stage_plan, pick_pipe_axis)
    out: List[Strategy] = []
    for S in _pipe_candidate_sizes(mesh):
        if pick_pipe_axis(mesh, S) is None or len(model.ops) < 2:
            continue
        stage_of = balanced_stages(model, S)
        if max(stage_of.values()) < 1:
            continue
        try:
            build_stage_plan(model, stage_of)  # stateful ops etc.
        except (ValueError, NotImplementedError):
            continue
        s = _pin_free_strategy(mesh)
        for op in model.ops:
            if op.op_type == "distributed_embedding":
                continue  # table placement has its own executable form
            s.set(op.name, OpStrategy({DEVICE_KEY: (stage_of[op.name],)}))
        out.append(s)
    return out


def _divisor_splits(n: int, num_axes: int):
    """All tuples (d0..dk) with product n, each di >= 1."""
    if num_axes == 1:
        yield (n,)
        return
    d = 1
    while d <= n:
        if n % d == 0:
            for rest in _divisor_splits(n // d, num_axes - 1):
                yield (d,) + rest
        d += 1


def enumerate_mesh_shapes(n_devices: int, model, cfg
                          ) -> List[Dict[str, int]]:
    """Candidate mesh factorizations of `n_devices` over the axes this
    model + the search gates can actually use.

    The degree analog of the reference sampling ND part counts
    (`get_random_parallel_config` model.cc:512; linear.cu:1074-1107
    out-channel divisors): the TPU strategy space fixes degrees via the
    mesh, so searching degrees = searching mesh shapes."""
    op_types = {op.op_type for op in model.ops}
    axes = ["data"]
    if ((cfg.enable_parameter_parallel or cfg.enable_attribute_parallel)
            and op_types & {"linear", "conv2d", "multihead_attention",
                            "embedding", "lstm", "moe_ffn"}):
        axes.append("model")
    if (cfg.enable_sequence_parallel
            and op_types & {"multihead_attention", "linear", "lstm",
                            "moe_ffn"}):
        axes.append("seq")
    if cfg.enable_expert_parallel and "moe_ffn" in op_types:
        axes.append("expert")
    if cfg.enable_pipeline_parallel and (
            "pipeline_blocks" in op_types or len(model.ops) >= 2):
        axes.append("pipe")
    shapes = []
    seen = set()
    for split in _divisor_splits(n_devices, len(axes)):
        # drop size-1 axes (except data, which names the default axis)
        shape = {ax: s for ax, s in zip(axes, split)
                 if s > 1 or ax == "data"}
        key = tuple(sorted(shape.items()))
        if key not in seen:
            seen.add(key)
            shapes.append(shape)
    return shapes


def optimize_with_mesh(model, budget: int = 1000, alpha: float = 0.05,
                       devices=None, seed: Optional[int] = None,
                       verbose: bool = False,
                       chains: Optional[int] = None):
    """Search strategy AND mesh factorization jointly: enumerate mesh
    shapes of the device count, anneal within each, return the
    (strategy, mesh) pair with the best simulated step time.

    Reference analog: the MCMC search samples parallel DEGREES per op
    (model.cc:512); GSPMD fixes degrees at mesh construction, so the
    degree search moves to the outer loop. Activated by
    --search-mesh-shapes (FFConfig.search_mesh_shapes).

    Mesh-shape candidates are distributed over a thread pool (the
    annealing phase mutates no shared config state and the per-op cost
    caches are shared read-mostly stores); the interleaved-pipeline
    upgrade — which prices candidates THROUGH the config knobs — runs
    serially afterwards, per shape."""
    import jax

    from ..parallel.mesh import make_mesh

    if devices is None:
        devices = (list(model.mesh.devices.flat) if model.mesh is not None
                   else list(jax.devices()))
    n = len(devices)
    cfg = model.config
    if seed is None:
        seed = int(getattr(cfg, "seed", 0) or 0)
    shapes = enumerate_mesh_shapes(n, model, cfg)
    t0 = time.perf_counter()
    # budget is the TOTAL iteration count across all factorizations
    # (reference --budget semantics): a per-shape floor would silently
    # multiply a deliberately small budget several-fold
    per_budget = max(1, budget // max(1, len(shapes)))
    # optimize() records an interleaved-pipeline win on the config
    # knobs (_interleaved_upgrade) — snapshot/restore them per shape so
    # one shape's win cannot distort another shape's pricing, then
    # re-apply only the WINNING shape's knobs at the end
    base_knobs = (cfg.pipeline_stages, cfg.pipeline_virtual_stages)

    def anneal_shape(shape):
        mesh = make_mesh(tuple(shape.values()), tuple(shape.keys()),
                         devices)
        sim = Simulator(
            model, mesh,
            calibrated_machine_model(
                mesh, machine_file=cfg.machine_model_file))
        found, cost, sim, stats = _optimize_impl(
            model, per_budget, alpha, mesh, seed, False, sim, None,
            chains=1)
        if cost is None:
            cost = sim.simulate(found)
        return shape, mesh, sim, found, cost, stats

    workers = min(max(1, len(shapes)), _resolve_chains(cfg, chains))
    if workers > 1 and len(shapes) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=workers) as pool:
            annealed = list(pool.map(anneal_shape, shapes))
    else:
        annealed = [anneal_shape(s) for s in shapes]

    best = None  # (cost, strategy, mesh, sim, pipeline_knobs, stats)
    agg_stats: Dict[str, object] = {}
    for shape, mesh, sim, found, cost, stats in annealed:
        strat = _interleaved_upgrade(model, cfg, mesh, sim, found,
                                     best_cost=cost, verbose=False)
        if strat is not found:  # upgrade won: re-price under its knobs
            cost = sim.simulate(strat)
        knobs = (cfg.pipeline_stages, cfg.pipeline_virtual_stages)
        cfg.pipeline_stages, cfg.pipeline_virtual_stages = base_knobs
        _merge_stats(agg_stats, stats)
        if verbose:
            print(f"[search/mesh] {shape}: {cost*1e3:.3f} ms/step")
        if best is None or cost < best[0]:
            best = (cost, strat, mesh, sim, knobs, stats)
    cfg.pipeline_stages, cfg.pipeline_virtual_stages = best[4]
    # _merge_stats last-wins on nested dicts; the convergence trace the
    # report should show is the WINNING shape's walk, not the last one
    if "trace" in best[5]:
        agg_stats["trace"] = best[5]["trace"]
    if verbose:
        print(f"[search/mesh] best: {dict(best[2].shape)} "
              f"at {best[0]*1e3:.3f} ms/step")
    if cfg.taskgraph_file:  # re-export for the WINNING mesh (inner runs
        # each wrote their own shape's graph; last is not best)
        best[3].simulate(best[1], dot_path=cfg.taskgraph_file)
    _export_schedule_trace(cfg, best[3], best[1], agg_stats)
    best[3].flush_cost_cache()
    # per-shape wall times overlap in the pool — summing them (what
    # _merge_stats did for the counters) would understate proposals/sec
    # by the worker count; report real elapsed time instead
    agg_stats["wall_s"] = time.perf_counter() - t0
    agg_stats["mesh_shapes"] = len(shapes)
    agg_stats["chains"] = 1  # per-shape annealing runs single-chain
    props = agg_stats.get("proposals", 0)
    agg_stats["proposals_per_sec"] = (props / agg_stats["wall_s"]
                                      if agg_stats["wall_s"] > 0 else 0.0)
    model.search_stats = agg_stats
    return best[1], best[2]


def _merge_stats(agg: Dict[str, object], stats: Dict[str, object]) -> None:
    """Accumulate one search's counters into an aggregate report dict
    (numeric fields add; nested dicts merge; everything else last-wins)."""
    for k, v in stats.items():
        if isinstance(v, (int, float)) and isinstance(agg.get(k), (int,
                                                                   float)):
            agg[k] = agg[k] + v
        elif isinstance(v, dict):
            agg[k] = dict(v)
        else:
            agg[k] = v
    if "wall_s" in agg and agg.get("proposals"):
        agg["proposals_per_sec"] = (agg["proposals"] / agg["wall_s"]
                                    if agg["wall_s"] > 0 else 0.0)


def _interleaved_upgrade(model, cfg, mesh, sim, best, best_cost=None,
                         verbose=False):
    """Search the virtual-stage dimension: price auto-cut interleaved
    pipelines (D devices x v chunks, v in {2, 4}) against the per-op
    search winner through the same tick-table pricing the executor's
    schedule defines (simulator._price_1f1b_ticks). The v dimension
    cannot ride a Strategy — pins express at most one stage per device
    — so, like optimize_with_mesh returning a mesh, a win is recorded
    on the CONFIG knobs compile's auto-cut lowering reads
    (pipeline_stages, pipeline_virtual_stages) and the returned
    strategy carries no pins. Gated exactly like the executor:
    interleaving requires the 1f1b schedule."""
    if mesh is None or not getattr(cfg, "enable_pipeline_parallel",
                                   False):
        return best
    if getattr(cfg, "pipeline_schedule", "gpipe") != "1f1b":
        return best
    if any(op.op_type == "pipeline_blocks" for op in model.ops):
        return best
    from ..parallel.graph_pipeline import pick_pipe_axis
    base_knobs = (cfg.pipeline_stages, cfg.pipeline_virtual_stages)
    pin_free = _pin_free_strategy(mesh)
    if best_cost is None:
        best_cost = sim.simulate(best)
    win = None
    try:
        for D in _pipe_candidate_sizes(mesh):
            if pick_pipe_axis(mesh, D) is None:
                continue
            for v in (2, 4):
                cfg.pipeline_stages = D
                cfg.pipeline_virtual_stages = v
                stage_of = sim._staged_assignment(pin_free)
                if stage_of is None or \
                        max(stage_of.values()) + 1 != D * v:
                    continue  # graph too small for D*v real stages
                c = sim.simulate(pin_free)
                if c < best_cost:
                    best_cost, win = c, (D, v)
                    if verbose:
                        print(f"[search] interleaved pipeline wins: "
                              f"{D} devices x v={v} "
                              f"{c*1e3:.3f} ms/step")
    finally:
        cfg.pipeline_stages, cfg.pipeline_virtual_stages = base_knobs
    if win is None:
        return best
    cfg.pipeline_stages, cfg.pipeline_virtual_stages = win
    # carried on the strategy too, so --export round-trips the whole
    # plan (pins cannot express v stages per device)
    pin_free.pipeline = {
        "stages": win[0], "virtual_stages": win[1],
        "schedule": "1f1b",
        "microbatches": int(getattr(cfg, "pipeline_microbatches", 4)),
    }
    return pin_free


def _anneal_chain(model, sim: Simulator, cands, staged, edges,
                  searchable, init: Strategy, init_cost: float,
                  budget: int, alpha: float, seed: int,
                  verbose: bool, chain: int = 0, trace=None):
    """One annealing chain (the body of the reference FFModel::optimize
    loop, model.cc:1905-1968) over `sim`. Proposal costs come from the
    DELTA path (simulate_delta: re-cost only the moved op, replay the
    cached scheduled task graph) whenever the template applies; moves
    that change task-graph structure — staged jumps, pipeline-expansion
    or placement flips — fall back to a full simulate() and rebase the
    template. A periodic re-sync full-simulates the current strategy
    and counts any divergence (stats["drift_resyncs"]); the delta
    replay is exact, so a nonzero count means a bug, not noise.

    `trace` (search/trace.SearchTrace) records every proposal — pure
    observation AFTER each accept decision, so traced walks consume
    the RNG identically to untraced ones (bit-identical results)."""
    cfg = model.config
    rng = random.Random(seed)
    current = init.copy()
    cur_cost = init_cost
    best, best_cost = current.copy(), cur_cost
    delta_on = sim.delta_rebase(current)
    if trace is not None:
        trace.record_best(-1, chain, best_cost)

    reset_every = max(1, budget // 100)
    resync_every = max(64, reset_every)
    for it in range(budget):
        if it > 0 and it % reset_every == 0 and cur_cost > best_cost:
            current, cur_cost = best.copy(), best_cost
            delta_on = sim.delta_rebase(current)
        elif delta_on and it > 0 and it % resync_every == 0:
            # periodic drift re-sync: ground the delta-tracked cost in
            # a full simulation (guards template-splicing bugs; the
            # replay is exact, so any divergence counted here is a bug)
            full = sim.simulate(current)
            if not math.isclose(full, cur_cost, rel_tol=1e-9,
                                abs_tol=1e-15):
                sim.stats["drift_resyncs"] += 1
                cur_cost = full
                delta_on = sim.delta_rebase(current)

        # global staged-pipeline move: jump to (or mutate microbatching
        # of) a whole-graph stage cut — per-op moves cannot assemble a
        # viable pipeline one pin at a time
        if staged and rng.random() < 0.1:
            nxt = rng.choice(staged).copy()
            nxt_cost = sim.simulate(nxt)
            delta = nxt_cost - cur_cost
            temp = alpha * cur_cost
            accepted = delta <= 0 or rng.random() < math.exp(
                -delta / max(1e-12, temp))
            if accepted:
                current, cur_cost = nxt, nxt_cost
                delta_on = sim.delta_rebase(current)
                if cur_cost < best_cost:
                    best, best_cost = current.copy(), cur_cost
                    if trace is not None:
                        trace.record_best(it, chain, best_cost)
                    if verbose:
                        print(f"[search] iter {it}: staged pipeline "
                              f"{best_cost*1e3:.3f} ms/step")
            if trace is not None:
                trace.record(it, chain, "staged", None, delta,
                             accepted, temp, "full")
            continue
        # rewrite/propagate moves mutate `current` IN PLACE (one op's
        # entry swapped, restored on rejection) — copying the whole
        # strategy per proposal costs more than the delta simulation
        # itself at small-graph scale
        # propagation move is opt-in (reference --enable-propagation,
        # model.cc:2374), fired with prob 0.25 like model.cc:1807-1903
        if cfg.enable_propagation and rng.random() < 0.25 and edges:
            # propagate along a random edge (reference propagation move)
            src, dst = rng.choice(edges)
            m = current.for_op(src.name).axis_map
            if m in cands.get(dst.name, []):
                changed, new_map = dst.name, dict(m)
                kind = "propagate"
            else:
                op = rng.choice(searchable)
                changed = op.name
                new_map = dict(rng.choice(cands[op.name]))
                kind = "rewrite"
        else:
            op = rng.choice(searchable)
            changed = op.name
            new_map = dict(rng.choice(cands[op.name]))
            kind = "rewrite"
        # .get: after an accepted staged jump `current` only carries
        # the pinned ops' entries (for_op falls back to the default)
        prev = current.op_strategies.get(changed)
        current.set(changed, OpStrategy(new_map))

        tok = sim.simulate_delta(current, (changed,)) if delta_on else None
        nxt_cost = tok.cost if tok is not None else sim.simulate(current)
        delta = nxt_cost - cur_cost
        temp = alpha * cur_cost
        accepted = delta <= 0 or rng.random() < math.exp(
            -delta / max(1e-12, temp))
        if accepted:
            cur_cost = nxt_cost
            if tok is None:
                # structural move accepted outside the template
                delta_on = sim.delta_rebase(current)
            if cur_cost < best_cost:
                best, best_cost = current.copy(), cur_cost
                if trace is not None:
                    trace.record_best(it, chain, best_cost)
                if verbose:
                    print(f"[search] iter {it}: {best_cost*1e3:.3f} ms/step")
        else:
            if prev is None:
                del current.op_strategies[changed]
            else:
                current.op_strategies[changed] = prev
            if tok is not None:
                sim.delta_reject(tok)
        if trace is not None:
            trace.record(it, chain, kind, changed, delta, accepted,
                         temp, "delta" if tok is not None else "full")

    if verbose:
        print(f"[search] chain {chain} best estimated step time: "
              f"{best_cost*1e3:.3f} ms")
    return best, best_cost


def _optimize_impl(model, budget: int, alpha: float, mesh, seed: int,
                   verbose: bool, simulator: Optional[Simulator],
                   use_native: Optional[bool], chains: int):
    """Engine dispatch + annealing; returns (best, best_cost, sim,
    stats) with NO config-knob side effects (the interleaved upgrade
    and taskgraph export stay with the caller, so mesh-shape sweeps
    and chains can run this concurrently)."""
    cfg = model.config
    # fused searches must anneal in the Python engine (the native table
    # cannot price fusion folding); optimize() raises on an explicit
    # use_native=True, every other caller (incl. optimize_with_mesh's
    # per-shape runs) gets coerced here
    if cfg.perform_fusion and use_native is not True:
        use_native = False
    sim = simulator or Simulator(
        model, mesh,
        calibrated_machine_model(mesh,
                                 machine_file=cfg.machine_model_file))
    # bucketed grad-sync pricing (grad_bucket_mb) exists only in the
    # Python event loop — the native table lowers one sync task per op;
    # anneal in Python so the search prices the overlap the executor
    # actually delivers (explicit use_native=True keeps the native walk
    # with its pre-bucket sync model)
    if (sim.overlap and sim.bucket_mb > 0
            and int(mesh.shape.get("data", 1)) > 1
            and use_native is not True):
        use_native = False

    cands = {op.name: candidate_maps(op, mesh, cfg, op_index=i)
             for i, op in enumerate(model.ops)}
    t0 = time.perf_counter()
    trace = None  # per-proposal search tracing (search/trace.py);
    # created once the per-chain budget is known below

    def stats_for(sims, proposals):
        out: Dict[str, object] = {}
        for s in sims:
            _merge_stats(out, s.search_stats())
        out["proposals"] = proposals
        out["chains"] = len(sims)
        out["wall_s"] = time.perf_counter() - t0
        out["proposals_per_sec"] = (proposals / out["wall_s"]
                                    if out["wall_s"] > 0 else 0.0)
        if trace is not None:
            out["trace"] = trace.summary()
        return out

    # graph-PP staged candidates: a staged strategy's simulated cost is
    # INDEPENDENT of the per-op assignment (the whole graph runs as one
    # pipeline), so the native engine needn't anneal through them — run
    # the native search over the per-op space and compare the winner
    # against each staged candidate afterward (priced by the Python
    # staged expansion). Equivalent outcome to the Python loop's global
    # staged moves, native speed retained.
    staged = staged_strategies(model, mesh, cfg)
    if use_native is not False:
        from .native_search import optimize_native
        found = optimize_native(model, sim, cands, budget, alpha, seed,
                                verbose=verbose)
        if found is not None:
            best = found
            best_cost = None
            if staged:  # compare only when candidates exist: the
                best_cost = sim.simulate(found)  # extra sim is theirs
                for st in staged:
                    c = sim.simulate(st)
                    if c < best_cost:
                        best, best_cost = st, c
                        if verbose:
                            print(f"[search] staged pipeline wins: "
                                  f"{best_cost*1e3:.3f} ms/step")
            return best, best_cost, sim, stats_for([sim], budget)
        assert use_native is not True, "native search requested but " \
            "the native library is unavailable"
    _, edges = op_edges(model)

    init = (model.strategy or Strategy()).copy()
    # materialize every op's map so moves are local
    for op in model.ops:
        init.set(op.name, init.for_op(op.name).copy())
    init_cost = sim.simulate(init)
    best, best_cost = init.copy(), init_cost

    # staged candidates compete even when no per-op axis choice exists
    for s in staged:
        c = sim.simulate(s)
        if c < best_cost:
            best, best_cost = s.copy(), c

    searchable = [op for op in model.ops if len(cands[op.name]) > 1]
    if not searchable or budget <= 0:
        return best, best_cost, sim, stats_for([sim], 0)

    # K independent chains over a shared read-only candidate set and
    # one process-wide persistent cost cache; the TOTAL budget is split
    # across chains (reference --budget semantics — chains diversify
    # the walk, they don't multiply the work) and the best strategy
    # across chains wins, ties to the lowest chain id for determinism.
    per_chain = max(1, budget // chains)
    if getattr(cfg, "search_trace", True):
        from .trace import SearchTrace
        trace = SearchTrace(budget=per_chain, chains=chains)
    sims = [sim] + [Simulator(model, mesh, sim.mm,
                              overlap_backward_sync=sim.overlap)
                    for _ in range(chains - 1)]
    for s_ in sims[1:]:
        s_.time_scale = sim.time_scale
        s_.step_overhead = sim.step_overhead

    def run_chain(k):
        return _anneal_chain(model, sims[k], cands, staged, edges,
                             searchable, init, init_cost, per_chain,
                             alpha, _chain_seed(seed, k), verbose,
                             chain=k, trace=trace)

    if chains == 1:
        results = [run_chain(0)]
    else:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=chains) as pool:
            results = list(pool.map(run_chain, range(chains)))
    for cb, cc in results:
        if cc < best_cost:
            best, best_cost = cb, cc
    return best, best_cost, sim, stats_for(sims, per_chain * chains)


def optimize(model, budget: int = 1000, alpha: float = 0.05,
             mesh=None, seed: Optional[int] = None, verbose: bool = False,
             simulator: Optional[Simulator] = None,
             use_native: Optional[bool] = None,
             chains: Optional[int] = None) -> Strategy:
    """Anneal over strategies; returns the best found.

    Reference contract: called from compile() when search_budget > 0
    (model.cc:1561-1570); unlike the reference we do NOT exit the process
    after search — the found strategy is used directly (and exported when
    --export is set).

    The annealing loop runs in the native C++ engine (csrc/mcmc.cc) when
    available — the analog of the reference keeping search+simulation in
    C++ — with this Python loop as the fallback.  `use_native=False`
    forces the Python path, which anneals K parallel chains
    (--search-chains) with delta re-simulation per move
    (Simulator.simulate_delta) and a shared persistent cost cache.

    `seed=None` resolves to FFConfig.seed, and ALL randomness flows
    through per-chain `random.Random` instances — same seed, same
    strategy, reproducibly. Search counters land on
    `model.search_stats` (profiling.search_report renders them)."""
    mesh = mesh or model.mesh
    if mesh is None:
        return model.strategy or Strategy()
    cfg = model.config
    if seed is None:
        seed = int(getattr(cfg, "seed", 0) or 0)
    # The native engine mirrors the Python simulator task-for-task —
    # including per-device resources for placed candidates and GPipe
    # event-loop expansion (csrc/mcmc.cc). The one remaining Python-only
    # capability is FUSION folding (same-strategy chains costed as one
    # task), so fused searches route to the Python engine.
    if cfg.perform_fusion:
        if use_native is True:
            raise ValueError("native search does not support "
                             "perform_fusion; use the Python engine")
        use_native = False
    best, best_cost, sim, stats = _optimize_impl(
        model, budget, alpha, mesh, seed, verbose, simulator,
        use_native, _resolve_chains(cfg, chains))
    # the interleaved-variant comparison and --taskgraph export run on
    # every return path; `best_cost` spares a re-simulation when known
    strategy = _interleaved_upgrade(model, cfg, mesh, sim, best,
                                    best_cost=best_cost, verbose=verbose)
    if cfg.taskgraph_file:
        sim.simulate(strategy, dot_path=cfg.taskgraph_file)
    _export_schedule_trace(cfg, sim, strategy, stats)
    sim.flush_cost_cache()
    model.search_stats = stats
    return strategy


def _export_schedule_trace(cfg, sim, strategy, stats) -> None:
    """--schedule-trace: Perfetto export of the winning strategy's
    simulated event-loop schedule (Simulator.export_schedule), summary
    stashed in the search stats. An unwritable path must not fail the
    search that found the strategy."""
    path = getattr(cfg, "schedule_trace_file", None)
    if not path:
        return
    try:
        stats["schedule_trace"] = sim.export_schedule(strategy, path)
    except OSError as e:
        import warnings
        warnings.warn(f"schedule-trace export to {path!r} failed "
                      f"({type(e).__name__}: {e})")
