"""MCMC search tracing: per-proposal events + convergence diagnostics.

The search is the paper's contribution, and until this module it was a
black box: optimize() returned a strategy with no record of WHY — which
moves were proposed, what the simulator priced them at, where the walk
converged. A :class:`SearchTrace` rides one optimize /
optimize_with_mesh / optimize_serve call, recording every proposal
(iteration, chain, op(s) moved, delta-cost, accept/reject, the
Metropolis temperature, and whether the delta or the full simulation
path priced it) into a bounded per-chain ring, plus each chain's
best-cost curve.

Contract (gated in tools/explain.py --smoke / ci.sh): tracing is pure
host-side observation — a traced search is bit-identical to an
untraced one at the same seed (recording never touches the RNG, the
simulator, or any jitted program), the rings are bounded so a
million-proposal search cannot grow host memory without limit, and
``summary()`` is DETERMINISTIC under parallel chains: every chain
mutates only its own stats object (no cross-thread counters to race
on) and the merge orders by (iteration, chain), never by thread
interleaving. ``summary()`` is what profiling.search_report renders
and tools/search_bench.py records into BENCH_search.json.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

__all__ = ["SearchTrace"]

# event tuple layout (kept a tuple append — same hot-path discipline as
# utils/telemetry.Telemetry): (iteration, chain, kind, ops, delta_cost,
# accepted, temperature, path)
_F_ITER, _F_CHAIN, _F_KIND, _F_OPS, _F_DELTA, _F_ACC, _F_TEMP, \
    _F_PATH = range(8)


class _ChainStats:
    """One chain's accounting — touched by exactly one thread."""

    __slots__ = ("events", "dropped", "proposals", "accepts",
                 "by_path", "by_phase", "curve", "best")

    def __init__(self, max_events: int, phases: int):
        self.events: deque = deque(maxlen=max_events)
        self.dropped = 0
        self.proposals = 0
        self.accepts = 0
        self.by_path: Dict[str, List[int]] = {}
        self.by_phase = [[0, 0] for _ in range(phases)]
        self.curve: List[tuple] = []   # (iteration, cost) improvements
        self.best = float("inf")


class SearchTrace:
    """Bounded per-proposal event rings for one search run.

    One instance is shared by every chain of the run; each chain's
    events/counters live in its own :class:`_ChainStats` (created via
    the GIL-atomic ``dict.setdefault``), so parallel chains never race
    and the summary is reproducible. Phases for the
    acceptance-by-phase diagnostic are thirds of the per-chain budget —
    the standard annealing burn-in / search / refine split."""

    MAX_EVENTS = 65536
    PHASES = 3
    CURVE_TAIL = 32

    def __init__(self, budget: int = 0, chains: int = 1,
                 max_events: Optional[int] = None):
        self.budget = max(1, int(budget))
        self.max_events_per_chain = max(
            1, int(max_events or self.MAX_EVENTS) // max(1, int(chains)))
        self._chains: Dict[int, _ChainStats] = {}

    def _chain(self, chain: int) -> _ChainStats:
        st = self._chains.get(chain)
        if st is None:
            st = self._chains.setdefault(
                chain, _ChainStats(self.max_events_per_chain,
                                   self.PHASES))
        return st

    # ------------- recording (hot path: one append) --------------------
    def record(self, iteration: int, chain: int, kind: str, ops,
               delta_cost: float, accepted: bool, temperature: float,
               path: str) -> None:
        """One proposal. ``kind`` is the move type (rewrite / propagate
        / staged / serve_place), ``ops`` the op name(s) the move
        touched, ``path`` "delta" when Simulator.simulate_delta priced
        it, "full" for a full event-loop simulation."""
        st = self._chain(chain)
        if len(st.events) == st.events.maxlen:
            st.dropped += 1
        st.events.append((iteration, chain, kind, ops, delta_cost,
                          accepted, temperature, path))
        st.proposals += 1
        p = st.by_path.setdefault(path, [0, 0])
        p[0] += 1
        phase = min(self.PHASES - 1,
                    max(0, iteration) * self.PHASES // self.budget)
        st.by_phase[phase][0] += 1
        if accepted:
            st.accepts += 1
            p[1] += 1
            st.by_phase[phase][1] += 1

    def record_best(self, iteration: int, chain: int,
                    cost: float) -> None:
        """A new chain-best simulated cost (the convergence curve; the
        run-wide curve is merged deterministically in summary())."""
        st = self._chain(chain)
        if cost < st.best:
            st.best = cost
            st.curve.append((int(iteration), float(cost)))

    # ------------- diagnostics -----------------------------------------
    def summary(self, curve_tail: Optional[int] = None) -> dict:
        """The machine-readable convergence diagnostics search_report
        renders and BENCH_search.json records: acceptance rate overall
        / by phase / by simulation path, the run-wide best-cost-curve
        tail (chain curves merged by (iteration, chain) — thread-
        interleaving cannot change it), and the ring accounting."""
        chains = [self._chains[k] for k in sorted(self._chains)]
        proposals = sum(c.proposals for c in chains)
        accepts = sum(c.accepts for c in chains)
        by_phase = [[0, 0] for _ in range(self.PHASES)]
        by_path: Dict[str, List[int]] = {}
        for c in chains:
            for i, (p, a) in enumerate(c.by_phase):
                by_phase[i][0] += p
                by_phase[i][1] += a
            for path, (p, a) in c.by_path.items():
                t = by_path.setdefault(path, [0, 0])
                t[0] += p
                t[1] += a
        # run-wide best-cost curve: all chain improvements ordered by
        # (iteration, chain id), filtered to running improvements
        entries = sorted(
            (it, k, cost)
            for k in sorted(self._chains)
            for it, cost in self._chains[k].curve)
        curve = []
        best = float("inf")
        for it, k, cost in entries:
            if cost < best:
                best = cost
                curve.append({"iteration": it, "chain": k,
                              "cost_s": cost})
        tail = int(curve_tail or self.CURVE_TAIL)
        return {
            "proposals": proposals,
            "accepts": accepts,
            "acceptance_rate": accepts / proposals if proposals else 0.0,
            "acceptance_by_phase": [
                {"proposals": p, "accepts": a,
                 "rate": a / p if p else 0.0}
                for p, a in by_phase],
            "by_path": {
                path: {"proposals": p, "accepts": a}
                for path, (p, a) in sorted(by_path.items())},
            "best_cost_curve": curve[-tail:],
            "best_cost_s": curve[-1]["cost_s"] if curve else None,
            "improvements": len(curve),
            "events_recorded": sum(len(c.events) for c in chains),
            "events_dropped": sum(c.dropped for c in chains),
        }

    def events_list(self) -> List[dict]:
        """The retained rings as dicts, ordered by (chain, iteration)
        (debug / notebook use)."""
        return [{"iteration": e[_F_ITER], "chain": e[_F_CHAIN],
                 "kind": e[_F_KIND], "ops": e[_F_OPS],
                 "delta_cost": e[_F_DELTA], "accepted": e[_F_ACC],
                 "temperature": e[_F_TEMP], "path": e[_F_PATH]}
                for k in sorted(self._chains)
                for e in list(self._chains[k].events)]
