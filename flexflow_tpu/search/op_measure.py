"""Per-op, per-shape measured costs for the strategy search.

The reference times each op's REAL kernels at its actual sub-shapes at
search time (Op::measure_operator_cost -> inner_measure_operator_cost,
/root/reference/src/runtime/model.cu:20-62; per-shape cuDNN algorithm
selection conv_2d.cu:173-260; linear.cu:1000-1073). The analytic
roofline here prices families, not shapes — per-shape cliffs (small
GEMMs, odd conv geometries, 299-px Inception layers) are exactly where
family factors go wrong (VERDICT r3 #6).

This module grounds the top-N ops (by simulated time) in isolated-op
jit microbenchmarks: forward and forward+backward timed at the op's
data-sharded sub-shape, memoized in-process and persisted per device
kind (like measure.py's calibration cache) so each (op-signature,
shape) pair is timed once per machine, ever. Enabled with
FFConfig.measure_top_ops / --measure-ops N; the simulator then
overrides those ops' analytic fwd/bwd with measured seconds (residual
non-sample shardings still divide analytically).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..op import Op, OpContext

# (device_kind, signature) -> {"fwd": s, "bwd": s}
_MEMO: Dict[Tuple[str, str], Dict[str, float]] = {}
_DISK_LOADED: set = set()


def _cache_path(device_kind: str) -> str:
    from .measure import cache_file
    return cache_file("op_costs", device_kind)


def _load_disk(device_kind: str) -> None:
    if device_kind in _DISK_LOADED:
        return
    _DISK_LOADED.add(device_kind)
    try:
        with open(_cache_path(device_kind)) as f:
            for sig, v in json.load(f).items():
                _MEMO[(device_kind, sig)] = v
    except (OSError, json.JSONDecodeError):
        pass


def _persist(device_kind: str) -> None:
    path = _cache_path(device_kind)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # None = a FAILED measurement: in-process only, never persisted
        # (a cached failure would silently defeat re-measurement
        # forever — same policy as measure.py's calibrate())
        data = {sig: v for (kind, sig), v in _MEMO.items()
                if kind == device_kind and v is not None}
        with open(path, "w") as f:
            json.dump(data, f)
    except OSError:
        pass  # unwritable cache must not abort a search


def op_signature(op: Op, sample_shard: int) -> str:
    """Hashable measurement key: what the kernels see — op type, input
    shapes/dtypes at the sharded batch, weight shapes, and the attrs
    that change the computation."""
    ins = []
    for t in op.inputs:
        shape = list(t.shape)
        if shape and shape[0] % sample_shard == 0:
            shape[0] //= sample_shard
        ins.append((tuple(shape), str(np.dtype(t.dtype))))
    ws = sorted((w, tuple(s.shape), str(np.dtype(s.dtype)))
                for w, s in op.weight_specs().items())
    attrs = sorted((k, str(v)) for k, v in
                   getattr(op, "attrs", {}).items())
    return json.dumps([op.op_type, ins, ws, attrs])


def _device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def measure_op(op: Op, sample_shard: int = 1, repeats: int = 10,
               seq_length: int = -1) -> Optional[Dict[str, float]]:
    """Time `op` in isolation at its data-sharded sub-shape: jitted
    forward, and forward+backward via jax.grad (the executor's autodiff
    backward — matching what actually runs, where the reference timed
    its hand-written backward kernels). Returns {"fwd": s, "bwd": s}
    (bwd = the backward-only increment) or None when the op cannot be
    measured standalone. Memoized per (device kind, signature)."""
    kind = _device_kind()
    _load_disk(kind)
    sig = op_signature(op, sample_shard)
    if (kind, sig) in _MEMO:  # None = known-unmeasurable, also cached
        return _MEMO[(kind, sig)]

    import jax
    import jax.numpy as jnp

    def sub(shape):
        shape = list(shape)
        if shape and shape[0] % sample_shard == 0:
            shape[0] //= sample_shard
        return tuple(shape)

    try:
        xs = []
        float_idx = []
        for i, t in enumerate(op.inputs):
            dt = np.dtype(t.dtype)
            if np.issubdtype(dt, np.integer):
                xs.append(jnp.zeros(sub(t.shape), dt))
            else:
                xs.append(jnp.ones(sub(t.shape), dt) * 0.01)
                float_idx.append(i)
        params = {}
        for wname, spec in op.weight_specs().items():
            params[wname] = jnp.ones(spec.shape,
                                     np.dtype(spec.dtype)) * 0.01
        # stateful ops (BatchNorm running stats) read ctx.state_in —
        # feed init-valued state or every BN in a conv net silently
        # falls back to the analytic price (exactly the memory-bound
        # ops grounding exists to capture)
        state_in = {name: jnp.full(spec.shape, spec.init_value,
                                   np.dtype(spec.dtype))
                    for name, spec in op.state_specs().items()}
        rng = jax.random.PRNGKey(0)

        # differentiate w.r.t. params and FLOAT inputs only — integer
        # inputs (embedding/lookup indices) are non-differentiable and
        # would make jax.grad reject the whole op, silently dropping
        # exactly the gather/scatter ops grounding exists to capture
        def fwd(p, floats):
            full = list(xs)
            for i, v in zip(float_idx, floats):
                full[i] = v
            ctx = OpContext(training=True, rng=rng,
                            seq_length=seq_length, state_in=state_in,
                            mesh=None, op_strategy=None)
            ys = op.forward(p, full, ctx)
            return sum(jnp.sum(y.astype(jnp.float32)) for y in ys)

        floats = tuple(xs[i] for i in float_idx)
        f_jit = jax.jit(fwd)

        def timeit(fn, *args):
            out = fn(*args)
            float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = fn(*args)
            float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            return (time.perf_counter() - t0) / repeats

        t_fwd = timeit(f_jit, params, floats)
        if params or floats:
            argnums = (0, 1) if floats else (0,)
            g_jit = jax.jit(jax.grad(fwd, argnums=argnums))
            t_both = timeit(g_jit, params, floats)
        else:
            t_both = 2.0 * t_fwd  # nothing to differentiate: estimate
    except Exception:
        # stateful contracts, unexpected input coupling, non-diff ops —
        # the analytic cost stands for these
        _MEMO[(kind, sig)] = None
        return None
    res = {"fwd": t_fwd, "bwd": max(t_both - t_fwd, 0.2 * t_fwd)}
    _MEMO[(kind, sig)] = res
    _persist(kind)
    return res


def clear_memo() -> None:
    _MEMO.clear()
    _DISK_LOADED.clear()
