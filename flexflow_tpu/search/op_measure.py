"""Per-op, per-shape measured costs for the strategy search.

The reference times each op's REAL kernels at its actual sub-shapes at
search time (Op::measure_operator_cost -> inner_measure_operator_cost,
/root/reference/src/runtime/model.cu:20-62; per-shape cuDNN algorithm
selection conv_2d.cu:173-260; linear.cu:1000-1073). The analytic
roofline here prices families, not shapes — per-shape cliffs (small
GEMMs, odd conv geometries, 299-px Inception layers) are exactly where
family factors go wrong (VERDICT r3 #6).

This module grounds the top-N ops (by simulated time) in isolated-op
jit microbenchmarks: forward and forward+backward timed at the op's
data-sharded sub-shape, memoized in-process and persisted per device
kind (like measure.py's calibration cache) so each (op-signature,
shape) pair is timed once per machine, ever. Enabled with
FFConfig.measure_top_ops / --measure-ops N; the simulator then
overrides those ops' analytic fwd/bwd with measured seconds (residual
non-sample shardings still divide analytically).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..op import Op, OpContext

# (device_kind, signature) -> {"fwd": s, "bwd": s}
_MEMO: Dict[Tuple[str, str], Dict[str, float]] = {}
_DISK_LOADED: set = set()


def _cache_path(device_kind: str) -> str:
    from .measure import cache_file
    return cache_file("op_costs", device_kind)


def _load_disk(device_kind: str) -> None:
    if device_kind in _DISK_LOADED:
        return
    _DISK_LOADED.add(device_kind)
    try:
        with open(_cache_path(device_kind)) as f:
            for sig, v in json.load(f).items():
                _MEMO[(device_kind, sig)] = v
    except (OSError, json.JSONDecodeError):
        pass


def _persist(device_kind: str) -> None:
    path = _cache_path(device_kind)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # None = a FAILED measurement: in-process only, never persisted
        # (a cached failure would silently defeat re-measurement
        # forever — same policy as measure.py's calibrate())
        data = {sig: v for (kind, sig), v in _MEMO.items()
                if kind == device_kind and v is not None}
        with open(path, "w") as f:
            json.dump(data, f)
    except OSError:
        pass  # unwritable cache must not abort a search


def op_signature(op: Op, sample_shard: int) -> str:
    """Hashable measurement key: what the kernels see — op type, input
    shapes/dtypes at the sharded batch, weight shapes, and the attrs
    that change the computation."""
    ins = []
    for t in op.inputs:
        shape = list(t.shape)
        if shape and shape[0] % sample_shard == 0:
            shape[0] //= sample_shard
        ins.append((tuple(shape), str(np.dtype(t.dtype))))
    ws = sorted((w, tuple(s.shape), str(np.dtype(s.dtype)))
                for w, s in op.weight_specs().items())
    attrs = sorted((k, str(v)) for k, v in
                   getattr(op, "attrs", {}).items())
    return json.dumps([op.op_type, ins, ws, attrs])


def _device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def measure_op(op: Op, sample_shard: int = 1, repeats: int = 10,
               seq_length: int = -1) -> Optional[Dict[str, float]]:
    """Time `op` in isolation at its data-sharded sub-shape: jitted
    forward, and forward+backward via jax.grad (the executor's autodiff
    backward — matching what actually runs, where the reference timed
    its hand-written backward kernels). Returns {"fwd": s, "bwd": s}
    (bwd = the backward-only increment) or None when the op cannot be
    measured standalone. Memoized per (device kind, signature)."""
    kind = _device_kind()
    _load_disk(kind)
    sig = op_signature(op, sample_shard)
    if (kind, sig) in _MEMO:  # None = known-unmeasurable, also cached
        return _MEMO[(kind, sig)]

    import jax
    import jax.numpy as jnp

    def sub(shape):
        shape = list(shape)
        if shape and shape[0] % sample_shard == 0:
            shape[0] //= sample_shard
        return tuple(shape)

    try:
        xs = []
        float_idx = []
        for i, t in enumerate(op.inputs):
            dt = np.dtype(t.dtype)
            if np.issubdtype(dt, np.integer):
                xs.append(jnp.zeros(sub(t.shape), dt))
            else:
                xs.append(jnp.ones(sub(t.shape), dt) * 0.01)
                float_idx.append(i)
        params = {}
        for wname, spec in op.weight_specs().items():
            params[wname] = jnp.ones(spec.shape,
                                     np.dtype(spec.dtype)) * 0.01
        # stateful ops (BatchNorm running stats) read ctx.state_in —
        # feed init-valued state or every BN in a conv net silently
        # falls back to the analytic price (exactly the memory-bound
        # ops grounding exists to capture)
        state_in = {name: jnp.full(spec.shape, spec.init_value,
                                   np.dtype(spec.dtype))
                    for name, spec in op.state_specs().items()}
        rng = jax.random.PRNGKey(0)

        # differentiate w.r.t. params and FLOAT inputs only — integer
        # inputs (embedding/lookup indices) are non-differentiable and
        # would make jax.grad reject the whole op, silently dropping
        # exactly the gather/scatter ops grounding exists to capture
        def fwd(p, floats):
            full = list(xs)
            for i, v in zip(float_idx, floats):
                full[i] = v
            ctx = OpContext(training=True, rng=rng,
                            seq_length=seq_length, state_in=state_in,
                            mesh=None, op_strategy=None)
            ys = op.forward(p, full, ctx)
            return sum(jnp.sum(y.astype(jnp.float32)) for y in ys)

        floats = tuple(xs[i] for i in float_idx)
        f_jit = jax.jit(fwd)

        def timeit(fn, *args):
            out = fn(*args)
            float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = fn(*args)
            float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            return (time.perf_counter() - t0) / repeats

        t_fwd = timeit(f_jit, params, floats)
        if params or floats:
            argnums = (0, 1) if floats else (0,)
            g_jit = jax.jit(jax.grad(fwd, argnums=argnums))
            t_both = timeit(g_jit, params, floats)
        else:
            t_both = 2.0 * t_fwd  # nothing to differentiate: estimate
    except Exception:
        # stateful contracts, unexpected input coupling, non-diff ops —
        # the analytic cost stands for these
        _MEMO[(kind, sig)] = None
        return None
    res = {"fwd": t_fwd, "bwd": max(t_both - t_fwd, 0.2 * t_fwd)}
    _MEMO[(kind, sig)] = res
    _persist(kind)
    return res


# op types corrected by the conv-chain in-situ factor: the families
# whose isolated microbenchmarks under-predict in-graph cost (cache-warm
# single-op loops vs full-graph memory pressure; CPU table
# evidence/sim_validation_cpu.json showed conv models -35%/-52% while
# transformer sat at -4.6%, so the correction is scoped to conv chains)
CONV_CHAIN_TYPES = ("conv2d", "pool2d", "batch_norm")

_INSITU: Dict[str, float] = {}


def conv_in_situ_factor() -> float:
    """Transferable isolated->in-situ correction for conv-chain ops,
    measured ONCE per device kind and persisted: time one real train
    step of a fixed small conv-chain graph and divide by the sum of its
    ops' isolated measurements (same measure_op the simulator grounds
    with, so the bias cancels by construction on the micro-graph and
    transfers to bigger conv models as a scalar). Clamped to [1, 3];
    1.0 on any failure so grounding degrades to today's behavior.

    This is the per-op-type in-situ calibration VERDICT r4 #5 asks for
    — the analog of the reference measuring kernels under real Realm
    instance pressure rather than in a bare loop (model.cu:20-62)."""
    kind = _device_kind()
    if kind in _INSITU:
        return _INSITU[kind]
    path = _insitu_path(kind)
    try:
        with open(path) as f:
            # clamp on LOAD too: a corrupt/stale cache value (0, NaN,
            # 100) would otherwise zero out or explode every conv cost
            _INSITU[kind] = _clamp_insitu(float(json.load(f)["factor"]))
        return _INSITU[kind]
    except (OSError, json.JSONDecodeError, KeyError, ValueError,
            TypeError):
        pass
    factor = None
    try:
        factor = _measure_insitu_factor()
    except Exception:  # noqa: BLE001 — degrade to uncorrected grounding
        pass
    if factor is None:
        # FAILED measurement: in-process only, never persisted — a
        # cached failure would silently defeat re-measurement forever
        # (same policy as _persist for per-op failures)
        _INSITU[kind] = 1.0
        return 1.0
    factor = _clamp_insitu(factor)
    _INSITU[kind] = factor
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"factor": factor}, f)
    except OSError:
        pass
    return factor


def _clamp_insitu(f: float) -> float:
    if not np.isfinite(f):
        return 1.0
    return float(min(3.0, max(1.0, f)))


def _insitu_path(device_kind: str) -> str:
    from .measure import cache_file
    return cache_file("insitu", device_kind)


def _measure_insitu_factor() -> float:
    import jax
    import jax.numpy as jnp  # noqa: F401

    from ..config import FFConfig
    from ..core.optimizers import SGDOptimizer
    from ..model import FFModel

    # inception-like SPATIAL scale matters: the in-situ penalty grows
    # with activation footprint (32px ratio ~1.15, 75px ~1.46, 149px
    # ~1.56 on the CPU host — cache pressure the isolated loop never
    # sees), and the models this correction targets are exactly the
    # big-activation conv nets
    size = 149
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.sibling_conv_fusion = False  # measure the plain lowering
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16, size, size), name="input")
    t = ff.conv2d(x, 32, 3, 3, 1, 1, 1, 1, name="ins_c0")
    t = ff.batch_norm(t, name="ins_bn0")
    t = ff.conv2d(t, 64, 3, 3, 2, 2, 1, 1, activation="relu",
                  name="ins_c1")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="ins_p0")
    t = ff.flat(t, name="ins_flat")
    t = ff.dense(t, 10, name="ins_head")
    ff.softmax(t, name="ins_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    batch = {"input": rng.randn(8, 16, size, size).astype(np.float32),
             "label": rng.randint(0, 10, (8,)).astype(np.int32)}
    # device-resident ONCE: the isolated-op denominator times
    # device-resident arrays, so the numerator must not pay a per-step
    # host->device transfer of the 11MB batch — through the remote-TPU
    # tunnel that transfer dominates and would pin the factor at the
    # clamp (the round-4 per-dispatch-transfer trap, all over again)
    batch = ff.executor.shard_batch(batch)
    float(ff.train_batch(batch)["loss"])  # compile
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        m = ff.train_batch(batch)
    float(m["loss"])  # device->host sync (axon: only a fetch drains)
    real = (time.perf_counter() - t0) / reps

    # numerator hygiene: the real step carries per-dispatch overhead
    # (dominant through the remote-TPU tunnel — the simulator prices it
    # separately as step_overhead_s) which must not be attributed to
    # the conv ops; and if ANY op is unmeasurable the attribution
    # breaks, so bail to no-correction rather than inflate the ratio
    from .measure import measure_step_overhead
    real = max(0.0, real - measure_step_overhead(repeats=reps))

    isolated = 0.0
    for op in ff.ops:
        r = measure_op(op)
        if r is None:
            return None
        isolated += r["fwd"] + r["bwd"]
    if isolated <= 0 or real <= 0:
        return None
    return real / isolated


def clear_memo() -> None:
    _MEMO.clear()
    _DISK_LOADED.clear()
    _INSITU.clear()
