"""Unified telemetry: structured event bus, metrics, drift calibration.

FlexFlow's core bet is that an execution simulator can price real
placements accurately — but until this module nothing ever checked the
simulator's predictions against what the engine measures, and all
serving/training stats lived in ad-hoc ``last_stats`` dicts rendered
only as report strings. This module is the machine-readable layer
underneath (docs/observability.md):

  * :class:`Telemetry` — a low-overhead structured event bus. Spans,
    instants and counter samples land in a BOUNDED ring buffer
    (``collections.deque(maxlen=...)``) stamped from ONE monotonic
    clock; the hot-path cost of a record is a single tuple append
    (and a no-op attribute check when disabled). ServeEngine and
    fit()/DispatchWindow mark per-request lifecycle spans (queue-wait,
    prefill chunks, decode steps, preemption, speculation verify,
    retries, degradation rungs, cancel/deadline) and per-step train
    spans (dispatch, fetch-wait) on named (process, thread) tracks.
  * :class:`MetricsRegistry` — counters / gauges / histograms with
    nearest-rank quantiles, exported as a Prometheus-style text page
    (:meth:`~MetricsRegistry.to_prometheus`) or a JSON snapshot
    (:meth:`~MetricsRegistry.snapshot`). The canonical metric
    definitions live HERE (:func:`serve_metrics` /
    :func:`train_metrics`), and ``utils/profiling.serve_report`` /
    ``train_report`` are rendered FROM these snapshots — the string
    reports and the exported numbers cannot drift apart.
  * Chrome trace-event export (:meth:`Telemetry.export_chrome_trace`)
    — a ``chrome://tracing`` / Perfetto-loadable JSON with one track
    per request slot plus one per engine step stream (``--trace-out``).
  * The simulator-drift calibrator (:meth:`Telemetry.record_drift` /
    :meth:`drift_report`): each engine step records its measured wall
    time next to the cost model's predicted time for the same (batch
    composition, kv dtype, mesh degree) regime — via
    ``search/cost_model.serve_step_tasks`` +
    ``simulator.simulate_serve_step`` for serving and the bucketed
    overlap graph for training — and the report emits per-regime
    predicted/measured ratios, flagged when drift exceeds the
    configured threshold. This is the measurement substrate future
    machine-model recalibration (and the ROADMAP router/autoscaler)
    will trust.

Contract: telemetry on vs off is token-identical with zero recompiles
(everything here is host-side bookkeeping — no jax in the record path)
at <= 3% step-time overhead, gated in ci.sh step 1k; every site keeps
working under fault injection, so chaos runs become traceable.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "MetricsRegistry", "MetricsServer", "Telemetry", "telemetry_for",
    "pct", "pow2_bucket", "serve_metrics", "train_metrics",
    "next_trace_id", "attribute_request", "fold_attribution",
    "write_json_atomic", "REQUEST_COMPONENTS",
]


# ---------------------------------------------------------------------------
# Trace-context propagation (docs/observability.md "Trace-id
# propagation"): one process-wide counter mints a per-request trace id
# at the FIRST tier that sees the request — the router's submit, a
# DisaggCluster's generate, or the scheduler itself for a plain engine
# — and the id rides the Request / ServeSession / PageShipment through
# every engine it crosses, so every span of one request's life carries
# the same `trace` arg no matter which replica/role recorded it.
# ---------------------------------------------------------------------------
_TRACE_IDS = itertools.count(1)


def next_trace_id() -> int:
    """Mint a process-unique request trace id (monotonic int; `next`
    on an itertools.count is atomic under the GIL). Host bookkeeping
    only — minting never touches a jitted program, so the telemetry
    on == off token-identity contract is untouched."""
    return next(_TRACE_IDS)


def pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list — THE percentile
    definition of this repo (serve_report, serve_percentiles and every
    exported histogram quantile share it, so a report line and its
    BENCH record can never disagree)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def pow2_bucket(n: int) -> int:
    """Round up to a power of two (0 stays 0) — the drift calibrator's
    regime-bucketing for prefill lane counts and context lengths, so a
    long run collapses into a handful of comparable regimes instead of
    one regime per distinct step shape."""
    n = int(n)
    if n <= 0:
        return 0
    return 1 << (n - 1).bit_length()


def write_json_atomic(path: str, doc: dict) -> str:
    """Write a JSON document via tmp + rename so no partially-written
    artifact is ever visible (the checkpoint promote discipline applied
    to observability artifacts: traces, post-mortem bundles, snapshot
    dumps). Non-JSON-native values stringify rather than fail — a
    flight recorder must never crash on its own payload."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Per-request critical-path attribution (docs/observability.md
# "Per-request latency attribution"): fold one request's spans into an
# additive breakdown of where its measured latency went. The fold is an
# INTERVAL PARTITION of [t_submit, t_finish): every elementary segment
# of the request's wall life is assigned to exactly one component (the
# highest-priority interval covering it), so the components — plus the
# explicit "other" bucket for host/scheduling time no span covers — sum
# to the measured latency EXACTLY by construction (gated within 1%).
# ---------------------------------------------------------------------------

REQUEST_COMPONENTS = ("queue", "routing", "prefill", "transfer",
                      "decode", "preempt_stall", "retry",
                      "host_reload", "other")

# span name -> component for trace-matched spans
_SPAN_CLASS = {"prefill": "prefill", "decode": "decode",
               "spec_decode": "decode", "kv_handoff": "transfer",
               "host_reload": "host_reload", "routing": "routing"}
# overlap priority (highest wins per elementary segment): compute beats
# the queue-wait span that legitimately overlaps a request's FIRST
# chunk (t_admit is stamped after the admitting step's dispatch), a
# host-tier page reload (serve/host_tier.py) likewise happens inside
# the admitting schedule() pass so it must beat queue, and retry
# backoff carves time out of the compute span that covers it
_CLASS_PRIORITY = {"retry": 8, "decode": 7, "prefill": 6,
                   "transfer": 5, "host_reload": 4,
                   "preempt_stall": 3, "queue": 2, "routing": 1}


def attribute_request(events: Iterable[tuple], trace_id,
                      *, t_submit: float, t_finish: float) -> dict:
    """Attribute one request's measured latency across
    :data:`REQUEST_COMPONENTS` from raw telemetry ring tuples.

    `events` are ``(ph, track, name, ts, dur, ident, args)`` tuples on
    the TRACE clock; `t_submit` / `t_finish` must be on the same clock
    (:meth:`Telemetry.explain_request` rebases the Request's raw
    perf_counter stamps). Interval sources:

      * trace-matched ``X`` spans — prefill / decode / spec_decode
        chunk spans, ``kv_handoff`` transfer spans, the router's
        ``routing`` span;
      * trace-matched ``b``/``e`` async pairs — ``queue_wait`` (queue)
        and ``requeue_wait`` (preempt_stall); a pair still open at
        t_finish closes there (a request aborted while waiting);
      * ``retry_backoff`` spans carry no trace (a step's retry stalls
        every request in it) — their intersection with THIS request's
        compute spans is attributed to ``retry``.

    Returns ``{"trace_id", "latency_s", "components": {component:
    seconds}, "attributed_s"}`` where ``sum(components.values()) ==
    latency_s`` exactly (``other`` absorbs uncovered host time) and
    ``attributed_s`` is the span-covered (non-``other``) total."""
    t0, t1 = float(t_submit), float(t_finish)
    comps = {c: 0.0 for c in REQUEST_COMPONENTS}
    out = {"trace_id": trace_id, "latency_s": max(0.0, t1 - t0),
           "components": comps, "attributed_s": 0.0}
    if t1 <= t0:
        return out
    ivals: List[Tuple[str, float, float]] = []
    retry_ivals: List[Tuple[float, float]] = []
    open_async: Dict[Tuple[str, object], float] = {}
    for ph, _track, name, ts, dur, ident, args in events:
        tid = args.get("trace") if args else None
        if ph == "X":
            if name == "retry_backoff":
                retry_ivals.append((ts, ts + dur))
            cls = _SPAN_CLASS.get(name)
            if cls is not None and tid == trace_id:
                ivals.append((cls, ts, ts + dur))
        elif ph == "b" and tid == trace_id \
                and name in ("queue_wait", "requeue_wait"):
            open_async[(name, ident)] = ts
        elif ph == "e":
            s = open_async.pop((name, ident), None)
            if s is not None:
                ivals.append(("queue" if name == "queue_wait"
                              else "preempt_stall", s, ts))
    for (name, _ident), s in open_async.items():
        ivals.append(("queue" if name == "queue_wait"
                      else "preempt_stall", s, t1))
    clipped = [(cls, max(s, t0), min(e, t1))
               for cls, s, e in ivals if min(e, t1) > max(s, t0)]
    if retry_ivals:
        compute = [(s, e) for cls, s, e in clipped
                   if cls in ("prefill", "decode")]
        for rs, re_ in retry_ivals:
            for s, e in compute:
                s2, e2 = max(rs, s), min(re_, e)
                if e2 > s2:
                    clipped.append(("retry", s2, e2))
    bounds = sorted({t0, t1, *(x for _c, s, e in clipped
                               for x in (s, e))})
    for a, b in zip(bounds, bounds[1:]):
        mid = (a + b) / 2.0
        best = None
        for cls, s, e in clipped:
            if s <= mid < e and (best is None
                                 or _CLASS_PRIORITY[cls]
                                 > _CLASS_PRIORITY[best]):
                best = cls
        comps[best if best is not None else "other"] += b - a
    out["attributed_s"] = sum(v for c, v in comps.items()
                              if c != "other")
    return out


def fold_attribution(breakdown: dict, registry: "MetricsRegistry"
                     ) -> None:
    """Fold one request's attribution into a registry — the pool-level
    aggregate (`serve_latency_attribution_seconds_total{component}` /
    `serve_latency_attributed_requests_total` counters plus the
    derived `serve_latency_attribution_fraction{component}` gauges),
    so /metrics answers "where does this tier's latency GO" without
    re-walking the trace."""
    m = registry
    m.inc("serve_latency_attributed_requests_total")
    m.inc("serve_latency_attributed_seconds_total",
          breakdown["latency_s"])
    for comp, v in breakdown["components"].items():
        m.inc("serve_latency_attribution_seconds_total", v,
              component=comp)
    total = m.counter("serve_latency_attributed_seconds_total")
    for comp in REQUEST_COMPONENTS:
        v = m.counter("serve_latency_attribution_seconds_total",
                      component=comp)
        m.set("serve_latency_attribution_fraction",
              v / total if total > 0 else 0.0, component=comp)


def _label_key(labels: Dict[str, object]) -> str:
    """Prometheus-style series key: ``name{k="v",...}`` tail."""
    if not labels:
        return ""
    body = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return "{" + body + "}"


class MetricsRegistry:
    """Counters, gauges and histograms keyed by name + optional labels.

    Histograms keep exact count/sum totals plus a bounded window of
    recent samples (the quantile source — nearest-rank over the
    window, the same :func:`pct` the reports use). Everything is plain
    host Python. Mutation is guarded by ONE lock (`_lock`) so the
    wall-clock fabric's replica worker threads can increment shared
    counters without losing read-modify-write races; single-threaded
    behavior is unchanged (an uncontended acquire is ~100ns, inside
    the <= 3% recording-overhead gate). Readers take the same lock
    only for whole-registry exports (snapshot/to_prometheus) — point
    reads stay lock-free dict gets."""

    HIST_WINDOW = 4096

    def __init__(self, lock: Optional[threading.Lock] = None):
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self._hists: Dict[str, dict] = {}
        # shared with the owning Telemetry when there is one, so the
        # whole recording surface serializes on a single lock
        self._lock = lock if lock is not None else threading.Lock()

    # ---------------- recording ---------------------------------------
    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = name + _label_key(labels)
        with self._lock:
            self.counters[key] = self.counters.get(key, 0.0) \
                + float(value)

    def counter_set(self, name: str, value: float, **labels) -> None:
        """Absolute-set a counter — for sources that track their own
        cumulative totals (compile counts, fault-injector fired
        counts), where re-adding each snapshot would double-count."""
        with self._lock:
            self.counters[name + _label_key(labels)] = float(value)

    def set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self.gauges[name + _label_key(labels)] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        key = name + _label_key(labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {
                    "count": 0, "sum": 0.0,
                    "window": deque(maxlen=self.HIST_WINDOW)}
            h["count"] += 1
            h["sum"] += float(value)
            h["window"].append(float(value))

    # ---------------- reading -----------------------------------------
    def counter(self, name: str, default: float = 0.0, **labels) -> float:
        return self.counters.get(name + _label_key(labels), default)

    def gauge(self, name: str, default: float = 0.0, **labels) -> float:
        return self.gauges.get(name + _label_key(labels), default)

    def quantile(self, name: str, q: float, **labels) -> float:
        h = self._hists.get(name + _label_key(labels))
        if not h or not h["window"]:
            return 0.0
        return pct(sorted(h["window"]), q)

    def hist_count(self, name: str, **labels) -> int:
        h = self._hists.get(name + _label_key(labels))
        return int(h["count"]) if h else 0

    # ---------------- export ------------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready snapshot: every counter/gauge value plus each
        histogram's count/sum/min/max and p50/p90/p99 (nearest-rank
        over the retained window)."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hwins = {key: (h["count"], h["sum"], list(h["window"]))
                     for key, h in self._hists.items()}
        hists = {}
        for key, (count, total, window) in hwins.items():
            win = sorted(window)
            hists[key] = {
                "count": count, "sum": total,
                "min": win[0] if win else 0.0,
                "max": win[-1] if win else 0.0,
                "p50": pct(win, 50), "p90": pct(win, 90),
                "p99": pct(win, 99),
            }
        return {"counters": counters,
                "gauges": gauges,
                "histograms": hists}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (one ``# TYPE`` line per
        metric family; histogram quantiles as `{quantile="..."}`
        summary series plus `_count`/`_sum`)."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = {key: (h["count"], h["sum"], list(h["window"]))
                     for key, h in self._hists.items()}
        lines: List[str] = []
        fams = set()

        def family(key: str) -> str:
            return key.split("{", 1)[0]

        def type_line(key: str, typ: str) -> None:
            fam = family(key)
            if fam not in fams:
                fams.add(fam)
                lines.append(f"# TYPE {fam} {typ}")

        for key in sorted(counters):
            type_line(key, "counter")
            lines.append(f"{key} {counters[key]:g}")
        for key in sorted(gauges):
            type_line(key, "gauge")
            lines.append(f"{key} {gauges[key]:g}")
        for key in sorted(hists):
            count, total, window = hists[key]
            fam, _, tail = key.partition("{")
            base_labels = ("{" + tail) if tail else ""
            type_line(key, "summary")
            win = sorted(window)
            for q in (0.5, 0.9, 0.99):
                if base_labels:
                    series = (f"{fam}{base_labels[:-1]},"
                              f'quantile="{q}"}}')
                else:
                    series = f'{fam}{{quantile="{q}"}}'
                lines.append(f"{series} {pct(win, q * 100):g}")
            lines.append(f"{fam}_count{base_labels} {count}")
            lines.append(f"{fam}_sum{base_labels} {total:g}")
        return "\n".join(lines) + "\n"


class _DriftStat:
    """Accumulated predicted-vs-measured seconds for one regime.

    ``breakdown`` (optional) accumulates the predicted seconds per
    task CLASS for the regime — the attribution vector
    :meth:`Telemetry.task_drift_snapshot` aligns measured steps
    against."""

    __slots__ = ("predicted_s", "measured_s", "count", "breakdown")

    def __init__(self):
        self.predicted_s = 0.0
        self.measured_s = 0.0
        self.count = 0
        self.breakdown: Optional[Dict[str, float]] = None


class Telemetry:
    """The event bus + metrics + drift store one engine or model owns.

    Events are ``(ph, track, name, ts, dur, ident, args)`` tuples in a
    bounded ring (``max_events``); ``track`` is a (process, thread)
    string pair that the Chrome exporter maps to pid/tid. ``enabled``
    is checked by every caller BEFORE building the record, so a
    disabled Telemetry costs one attribute read per site."""

    # chaos-proof cap on drift regimes: a pathological workload cannot
    # grow the store without bound (drops are counted, never silent)
    MAX_DRIFT_REGIMES = 512

    def __init__(self, enabled: bool = True, max_events: int = 65536,
                 drift_threshold: float = 0.5,
                 t0: Optional[float] = None):
        self.enabled = bool(enabled)
        self.max_events = int(max_events)
        self.drift_threshold = float(drift_threshold)
        self.events: deque = deque(maxlen=self.max_events)
        # ONE lock serializes every mutation on this bus — metric
        # read-modify-writes, ring eviction accounting, drift-stat
        # accumulation — so replica worker threads (serve/router.py
        # wall-clock mode) share a Telemetry without losing updates
        self._lock = threading.Lock()
        self.metrics = MetricsRegistry(lock=self._lock)
        self.dropped_events = 0
        self._drift: Dict[Tuple[str, str], _DriftStat] = {}
        self.drift_regimes_dropped = 0
        # ONE monotonic clock zero for every span in the buffer. An
        # explicit `t0` pins the epoch instead — t0=0.0 makes every
        # recorder take trace-absolute seconds, which is how the
        # simulated-schedule exporters emit exact simulator times.
        self._t0 = time.perf_counter() if t0 is None else float(t0)

    # ---------------- clock -------------------------------------------
    def now(self) -> float:
        """Seconds on the trace clock (monotonic, zero at creation)."""
        return time.perf_counter() - self._t0

    def _rel(self, t: float) -> float:
        # callers pass raw perf_counter stamps; store trace-relative
        return t - self._t0

    # ---------------- recording (hot path: ONE append) ----------------
    def span(self, track: Tuple[str, str], name: str, t_start: float,
             t_end: float, args: Optional[dict] = None) -> None:
        """Complete span [t_start, t_end) (perf_counter stamps)."""
        if not self.enabled:
            return
        with self._lock:
            if len(self.events) == self.max_events:
                self.dropped_events += 1
            self.events.append(("X", track, name, self._rel(t_start),
                                max(0.0, t_end - t_start), None, args))

    def instant(self, track: Tuple[str, str], name: str,
                t: Optional[float] = None,
                args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            if len(self.events) == self.max_events:
                self.dropped_events += 1
            self.events.append(
                ("i", track, name,
                 self.now() if t is None else self._rel(t),
                 0.0, None, args))

    def async_span(self, track: Tuple[str, str], name: str, ident,
                   t_start: float, t_end: float,
                   args: Optional[dict] = None) -> None:
        """Async (b/e) span — the Chrome-trace form for intervals that
        legitimately overlap on one track (queue-wait of concurrently
        waiting requests)."""
        if not self.enabled:
            return
        with self._lock:
            n = len(self.events)
            if n >= self.max_events:        # both appends evict
                self.dropped_events += 2
            elif n == self.max_events - 1:  # the second append evicts
                self.dropped_events += 1
            self.events.append(("b", track, name, self._rel(t_start),
                                0.0, ident, args))
            self.events.append(("e", track, name, self._rel(t_end),
                                0.0, ident, None))

    def counter(self, track: Tuple[str, str], name: str, value: float,
                t: Optional[float] = None) -> None:
        """Counter-track sample (Perfetto renders these as a stepped
        line — pool occupancy, degradation rung)."""
        if not self.enabled:
            return
        with self._lock:
            if len(self.events) == self.max_events:
                self.dropped_events += 1
            self.events.append(
                ("C", track, name,
                 self.now() if t is None else self._rel(t),
                 float(value), None, None))

    def emit(self, events: Iterable[tuple]) -> None:
        """Bulk raw-event append — the per-step hot path of
        ServeEngine hands the WHOLE step's records over in one call
        instead of ~10 method calls. Each item is a finished
        ``(ph, track, name, t_abs, dur_or_value, ident, args)`` tuple
        whose timestamp is an ABSOLUTE perf_counter stamp; it is
        rebased to the trace clock here. Eviction accounting matches
        the one-at-a-time recorders: every event pushed out of the
        bounded ring (or unbuffered because the batch itself overflows
        it) counts as dropped."""
        if not self.enabled:
            return
        t0 = self._t0
        evs = [(ph, tr, nm, ts - t0, d, i, a)
               for ph, tr, nm, ts, d, i, a in events]
        with self._lock:
            over = len(self.events) + len(evs) - self.max_events
            if over > 0:
                self.dropped_events += over
            self.events.extend(evs)

    @contextlib.contextmanager
    def timed(self, track: Tuple[str, str], name: str,
              args: Optional[dict] = None):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.span(track, name, t0, time.perf_counter(), args)

    # ---------------- drift calibration --------------------------------
    def record_drift(self, domain: str, regime: str, predicted_s: float,
                     measured_s: float,
                     breakdown: Optional[Dict[str, float]] = None
                     ) -> None:
        """One step's measured wall time next to the cost model's
        predicted time for the same regime (a stable string of NAMED
        fields like ``"t=1 kv=float32 dec=4 pre=0 ctx=64"`` — named so
        drift_report reads without a decoder ring). ``breakdown``
        optionally carries the prediction's per-task-class seconds
        (``Simulator.step_breakdown`` / ``serve_step_breakdown``) for
        the attribution pass."""
        if not self.enabled:
            return
        key = (str(domain), str(regime))
        with self._lock:
            st = self._drift.get(key)
            if st is None:
                if len(self._drift) >= self.MAX_DRIFT_REGIMES:
                    self.drift_regimes_dropped += 1
                    return
                st = self._drift[key] = _DriftStat()
            st.predicted_s += float(predicted_s)
            st.measured_s += float(measured_s)
            st.count += 1
            if breakdown:
                if st.breakdown is None:
                    st.breakdown = {}
                b = st.breakdown
                for cls, v in breakdown.items():
                    b[cls] = b.get(cls, 0.0) + float(v)

    def drift_snapshot(self, threshold: Optional[float] = None) -> dict:
        """Per-regime predicted/measured accounting:
        ``{domain: {regime: {predicted_ms_per_step, measured_ms_per_step,
        ratio, count, flagged}}}`` where ``ratio`` is measured /
        predicted and ``flagged`` marks drift beyond ``threshold``
        (default: the construction-time threshold) in either
        direction — ratio above ``1 + threshold`` or below
        ``1 / (1 + threshold)``."""
        thr = self.drift_threshold if threshold is None else float(
            threshold)
        out: Dict[str, dict] = {}
        with self._lock:
            drift = dict(self._drift)
        for (domain, regime), st in drift.items():
            pred = st.predicted_s / st.count if st.count else 0.0
            meas = st.measured_s / st.count if st.count else 0.0
            ratio = (meas / pred) if pred > 0 else 0.0
            flagged = bool(
                pred > 0 and (ratio > 1.0 + thr
                              or ratio < 1.0 / (1.0 + thr)))
            out.setdefault(domain, {})[regime] = {
                "predicted_ms_per_step": pred * 1e3,
                "measured_ms_per_step": meas * 1e3,
                "ratio": ratio,
                "count": st.count,
                "flagged": flagged,
            }
        return out

    def task_drift_snapshot(self) -> dict:
        """Per-task-class drift attribution: fold the per-regime
        measured/predicted accounting down to ``{domain: {class:
        {predicted_s, attributed_measured_s, ratio}}}`` — turning
        "regime X is 1.4x off" into "the all-reduce term is 1.4x off",
        which is what ``measure.calibrate`` needs targeted at.

        Regimes mix the classes in different proportions, so the fold
        is an alignment, not a per-regime split: when enough regimes
        with distinct mixes exist, a least-squares solve of
        ``measured_r ~= sum_c ratio_c * predicted_{r,c}`` recovers the
        per-class scale factors (method "lstsq"); otherwise each
        regime's measured seconds are attributed to its classes by
        predicted share and the per-class totals ratioed (method
        "share"). Only regimes recorded WITH a breakdown
        participate."""
        by_domain: Dict[str, list] = {}
        with self._lock:
            drift = dict(self._drift)
        for (domain, _regime), st in drift.items():
            if st.breakdown and st.count:
                by_domain.setdefault(domain, []).append(st)
        out: Dict[str, dict] = {}
        for domain, stats in by_domain.items():
            classes = sorted({c for st in stats for c in st.breakdown})
            pred = {c: 0.0 for c in classes}
            attr = {c: 0.0 for c in classes}
            for st in stats:
                tot = sum(st.breakdown.values())
                for c in classes:
                    p = st.breakdown.get(c, 0.0)
                    pred[c] += p
                    # attribute the regime's measured seconds to its
                    # classes by predicted share
                    attr[c] += st.measured_s * (p / tot) if tot else 0.0
            ratios = {c: (attr[c] / pred[c]) if pred[c] > 0 else 0.0
                      for c in classes}
            method = "share"
            # solve only the classes that predicted ANY time: a class
            # every breakdown carries at 0.0 (an unified engine's
            # "transfer" column, a fits-in-HBM run's hbm_penalty) is an
            # all-zero column that would pin rank below full and lock
            # the solve out forever — its ratio is 0 by definition
            solve = [c for c in classes if pred[c] > 0.0]
            if len(stats) >= len(solve) >= 1:
                try:
                    import numpy as np
                    # weight regimes by sample count: X rows are the
                    # mean per-step class vectors, y the mean measured
                    X = np.array([[st.breakdown.get(c, 0.0) / st.count
                                   for c in solve] for st in stats])
                    y = np.array([st.measured_s / st.count
                                  for st in stats])
                    w = np.sqrt([st.count for st in stats])
                    sol, _, rank, _ = np.linalg.lstsq(
                        X * w[:, None], y * w, rcond=None)
                    if rank == len(solve) \
                            and np.all(np.isfinite(sol)):
                        ratios = {c: 0.0 for c in classes}
                        ratios.update({c: max(0.0, float(s))
                                       for c, s in zip(solve, sol)})
                        # keep the columns reconciled: under lstsq the
                        # attributed seconds ARE ratio * predicted, so
                        # attr/pred always equals the printed ratio
                        attr = {c: ratios[c] * pred[c] for c in classes}
                        method = "lstsq"
                except Exception:
                    pass  # attribution falls back to the share fold
            out[domain] = {
                "method": method,
                "regimes": len(stats),
                "classes": {c: {
                    "predicted_s": pred[c],
                    "attributed_measured_s": attr[c],
                    "ratio": ratios[c],
                } for c in classes},
            }
        return out

    def drift_report(self, threshold: Optional[float] = None) -> str:
        """Human rendering of :meth:`drift_snapshot` — per-regime
        measured/predicted ratios (regime keys are named
        ``dec=/pre=/ctx=``-style fields, never bare tuples) with a
        DRIFT flag past the threshold, followed by the per-task-class
        attribution table (:meth:`task_drift_snapshot`) when breakdowns
        were recorded. The flag is the recalibration signal: a TERM the
        machine model consistently mis-prices is exactly where
        ``measure.calibrate`` should spend its next measurement."""
        snap = self.drift_snapshot(threshold)
        if not snap:
            return "drift: no samples recorded"
        lines = [f"{'domain':8s} {'regime':44s} {'steps':>6s} "
                 f"{'pred ms':>9s} {'meas ms':>9s} {'meas/pred':>10s}"]
        for domain in sorted(snap):
            for regime in sorted(snap[domain]):
                r = snap[domain][regime]
                lines.append(
                    f"{domain:8s} {regime:44s} {r['count']:>6d} "
                    f"{r['predicted_ms_per_step']:>9.3f} "
                    f"{r['measured_ms_per_step']:>9.3f} "
                    f"{r['ratio']:>10.3f}"
                    + ("  DRIFT" if r["flagged"] else ""))
        if self.drift_regimes_dropped:
            lines.append(f"({self.drift_regimes_dropped} regimes past "
                         f"the {self.MAX_DRIFT_REGIMES}-regime cap "
                         f"dropped)")
        task = self.task_drift_snapshot()
        if task:
            thr = self.drift_threshold if threshold is None \
                else float(threshold)
            lines.append("")
            lines.append(
                f"{'domain':8s} {'task class':20s} {'pred s':>10s} "
                f"{'attr s':>10s} {'ratio':>7s}   (per-task drift "
                f"attribution)")
            for domain in sorted(task):
                t = task[domain]
                for cls in sorted(t["classes"]):
                    r = t["classes"][cls]
                    flag = r["ratio"] > 1.0 + thr or (
                        0.0 < r["ratio"] < 1.0 / (1.0 + thr))
                    lines.append(
                        f"{domain:8s} {cls:20s} "
                        f"{r['predicted_s']:>10.4f} "
                        f"{r['attributed_measured_s']:>10.4f} "
                        f"{r['ratio']:>7.3f}"
                        + ("  DRIFT" if flag else ""))
                lines.append(
                    f"{domain:8s} ({t['method']} over "
                    f"{t['regimes']} regime(s))")
        return "\n".join(lines)

    # ---------------- per-request views ---------------------------------
    def request_events(self, trace_id) -> List[tuple]:
        """Every buffered event of one request's causally-linked
        timeline: events whose args carry this ``trace`` id, plus the
        ``e`` closers of its async spans (which carry no args by
        design). Order is buffer (emission) order — timestamps within
        are on the ONE trace clock, so sorting by ts reconstructs the
        cross-engine timeline (router route -> queue_wait -> prefill
        chunks -> kv_handoff -> decode chunks) no matter which
        replica/role recorded each span."""
        out: List[tuple] = []
        open_idents = set()
        with self._lock:
            evs = list(self.events)
        for ev in evs:
            ph, _track, name, _ts, _dur, ident, args = ev
            if args is not None and args.get("trace") == trace_id:
                out.append(ev)
                if ph == "b":
                    open_idents.add((name, ident))
            elif ph == "e" and (name, ident) in open_idents:
                out.append(ev)
                open_idents.discard((name, ident))
        return out

    def explain_request(self, trace_id, t_submit: float,
                        t_finish: float) -> dict:
        """Per-request latency attribution over the buffered events
        (:func:`attribute_request`); `t_submit` / `t_finish` are the
        Request's RAW perf_counter stamps — rebased to the trace clock
        here, so the caller never touches the clock epoch."""
        with self._lock:
            evs = list(self.events)
        return attribute_request(
            evs, trace_id,
            t_submit=self._rel(t_submit), t_finish=self._rel(t_finish))

    def events_tail(self, n: int = 2048) -> List[list]:
        """The last `n` ring events in JSON-ready form (`[ph, [proc,
        thread], name, ts, dur, ident, args]`) — the flight recorder's
        bounded span payload."""
        with self._lock:
            evs = list(self.events)
        if n >= 0:
            evs = evs[-n:] if n else []
        return [[ph, list(track), name, ts, dur, ident, args]
                for ph, track, name, ts, dur, ident, args in evs]

    # ---------------- fault observability ------------------------------
    def record_faults(self, injector) -> None:
        """Export a FaultInjector's lifetime accounting (fired sites by
        kind, per-site hit counters) into the metrics registry, so
        chaos runs (ci.sh 1g) are inspectable post-hoc. Absolute-set:
        the injector already accumulates."""
        if not self.enabled or injector is None:
            return
        for site, kinds in getattr(injector, "fired", {}).items():
            for kind, n in kinds.items():
                self.metrics.counter_set("fault_fired_total", n,
                                         site=site, kind=kind)
        for site, n in getattr(injector, "_count", {}).items():
            self.metrics.counter_set("fault_site_hits_total", n,
                                     site=site)

    # ---------------- exporters ----------------------------------------
    def export_chrome_trace(self, path: str,
                            metadata: Optional[dict] = None) -> str:
        """Write the event buffer as Chrome trace-event JSON (the
        ``{"traceEvents": [...]}`` object form) loadable in Perfetto /
        ``chrome://tracing``. Tracks become pid/tid pairs with ``M``
        metadata naming them; ts/dur are microseconds on the trace
        clock. ``metadata`` lands under a top-level ``"metadata"`` key
        (ignored by viewers; how the simulated-schedule export stamps
        its exact makespan next to the display-unit events). Returns
        the path written."""
        pids: Dict[str, int] = {}
        tids: Dict[Tuple[str, str], int] = {}
        out: List[dict] = []
        with self._lock:
            evs = list(self.events)
        for ph, track, name, ts, dur, ident, args in evs:
            proc, thread = track
            pid = pids.setdefault(proc, len(pids) + 1)
            tid = tids.setdefault(track, len(tids) + 1)
            ev = {"ph": ph, "name": name, "pid": pid, "tid": tid,
                  "ts": ts * 1e6, "cat": proc}
            if ph == "X":
                ev["dur"] = dur * 1e6
            elif ph == "i":
                ev["s"] = "t"
            elif ph in ("b", "e"):
                ev["id"] = str(ident)
            elif ph == "C":
                ev["args"] = {name: dur}  # dur slot carries the value
            if args and ph != "C":
                ev["args"] = dict(args)
            out.append(ev)
        meta: List[dict] = []
        for proc, pid in pids.items():
            meta.append({"ph": "M", "name": "process_name", "pid": pid,
                         "tid": 0, "args": {"name": proc}})
        for (proc, thread), tid in tids.items():
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pids[proc], "tid": tid,
                         "args": {"name": thread}})
        doc = {"traceEvents": meta + out, "displayTimeUnit": "ms"}
        if metadata:
            doc["metadata"] = dict(metadata)
        # tmp + rename: no partially-written trace is visible
        return write_json_atomic(path, doc)

    def metrics_snapshot(self) -> dict:
        """The full machine-readable snapshot: metrics + drift + event
        accounting — what serve_bench/train_bench embed into their
        BENCH_*.json records."""
        return {
            "metrics": self.metrics.snapshot(),
            "drift": self.drift_snapshot(),
            "task_drift": self.task_drift_snapshot(),
            "events_buffered": len(self.events),
            "events_dropped": self.dropped_events,
        }

    def to_prometheus(self) -> str:
        return self.metrics.to_prometheus()

    def clear(self) -> None:
        with self._lock:
            self.events.clear()
            self.dropped_events = 0


class MetricsServer:
    """Live scrape endpoint: a stdlib ``http.server`` thread serving
    ``/metrics`` (Prometheus text from a callable — the engine's
    lifetime :class:`MetricsRegistry`) and ``/healthz`` (liveness).
    This is the hook a replica autoscaler polls (docs/observability.md
    "The metrics endpoint"); enabled by ``--metrics-port`` on FFConfig
    (port 0 binds an ephemeral port — ``self.port`` is the bound one).
    ``close()`` shuts the thread down cleanly and is idempotent; the
    serving hot path never touches the server (scrapes read the
    GIL-atomic registry from the server thread)."""

    def __init__(self, render, port: int = 0, host: str = "127.0.0.1"):
        import http.server
        import threading
        self._render = render

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(h):
                if h.path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain; charset=utf-8"
                elif h.path == "/metrics":
                    try:
                        body = str(render()).encode()
                    except Exception as e:  # a render bug must not
                        h.send_error(500, str(e))  # kill the thread
                        return
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    h.send_error(404)
                    return
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(h, *a):  # no per-scrape stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ff-metrics",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# one shared disabled instance: the off path costs an attribute read
_DISABLED = Telemetry(enabled=False, max_events=1)


def telemetry_for(config=None) -> Telemetry:
    """The Telemetry a subsystem should use (the ``injector_for``
    idiom): a FRESH enabled bus when ``config.telemetry``,
    ``config.trace_out``, ``config.metrics_port`` or
    ``config.postmortem_dir`` asks for one — each engine/model gets
    its own buffer — else the shared disabled instance (recording is
    a no-op attribute check). The flight recorder implies telemetry:
    a post-mortem bundle without the span ring would be a corpse with
    no black box."""
    if config is not None and (
            getattr(config, "telemetry", False)
            or getattr(config, "trace_out", None)
            or getattr(config, "postmortem_dir", None)
            or getattr(config, "metrics_port", None) is not None):
        return Telemetry(
            enabled=True,
            max_events=int(getattr(config, "telemetry_buffer_events",
                                   65536)),
            drift_threshold=float(getattr(config,
                                          "telemetry_drift_threshold",
                                          0.5)))
    return _DISABLED


# ---------------------------------------------------------------------------
# Canonical metric definitions — serve_report/train_report render FROM
# these snapshots, and the exporters publish the same registry, so the
# human report and the machine numbers share one source of truth.
# ---------------------------------------------------------------------------

def serve_metrics(stats: dict,
                  registry: Optional[MetricsRegistry] = None,
                  role: Optional[str] = None,
                  replica: Optional[str] = None,
                  tenant: Optional[str] = None) -> MetricsRegistry:
    """Fold one ServeEngine.last_stats dict into a MetricsRegistry:
    counters for tokens/requests/robustness events, gauges for
    rates/occupancy, histograms for TTFT / TPOT (per-token decode
    latency — each decode step's wall time divided over the tokens it
    produced, the batched-decode amortization) and request latency.
    Pass the engine's registry to ACCUMULATE across generate() calls
    (counters add, gauges overwrite, histograms extend); the default
    fresh registry is what serve_report renders from.

    ``role`` / ``replica`` fold the LABELED split instead
    (disaggregated serving's per-role split, serve/disagg.py, and the
    multi-replica router's per-replica split, serve/router.py): only
    the latency histograms and the core token/request counters, each
    under ``{role=...}`` / ``{replica=...}`` labels, so a
    DisaggCluster / ReplicaPool can split TTFT/TPOT percentiles per
    engine WITHOUT double-counting the unlabeled aggregates — the
    same no-double-counting fold for both label axes, which is what
    lets the autoscaler and disagg_report/router_report read
    per-engine latency from ONE registry instead of scraping engines
    individually (docs/observability.md). ``tenant`` is the third
    label axis (multi-tenant adapter serving, serve/adapters.py):
    fold a tenant-filtered stats dict under ``{tenant=...}`` to split
    latency and token counters per adapter tenant without touching
    the unlabeled aggregates."""
    m = registry if registry is not None else MetricsRegistry()
    lab = {}
    if role is not None:
        lab["role"] = str(role)
    if replica is not None:
        lab["replica"] = str(replica)
    if tenant is not None:
        lab["tenant"] = str(tenant)
    if lab:
        for r in stats.get("requests", []):
            m.inc("serve_requests_total",
                  outcome=r.get("outcome", "completed"), **lab)
            if r.get("ttft_s") is not None:
                m.observe("serve_ttft_seconds", r["ttft_s"], **lab)
            if r.get("latency_s") is not None:
                m.observe("serve_request_latency_seconds",
                          r["latency_s"], **lab)
        for t, w in zip(stats.get("decode_step_times_s", []),
                        stats.get("decode_widths", [])):
            if w > 0:
                m.observe("serve_tpot_seconds", t / w, **lab)
        m.inc("serve_tokens_generated_total",
              stats.get("total_new_tokens", 0), **lab)
        m.inc("serve_engine_steps_total", stats.get("steps", 0), **lab)
        m.inc("serve_decode_steps_total",
              stats.get("decode_steps", 0), **lab)
        m.inc("serve_prefill_tokens_computed_total",
              stats.get("prefill_tokens_computed", 0), **lab)
        m.inc("serve_prefix_hit_tokens_total",
              stats.get("prefix_hit_tokens", 0), **lab)
        return m
    for r in stats.get("requests", []):
        m.inc("serve_requests_total",
              outcome=r.get("outcome", "completed"))
        if r.get("ttft_s") is not None:
            m.observe("serve_ttft_seconds", r["ttft_s"])
        if r.get("latency_s") is not None:
            m.observe("serve_request_latency_seconds", r["latency_s"])
    for t, w in zip(stats.get("decode_step_times_s", []),
                    stats.get("decode_widths", [])):
        if w > 0:
            m.observe("serve_tpot_seconds", t / w)
    m.inc("serve_tokens_generated_total",
          stats.get("total_new_tokens", 0))
    m.inc("serve_engine_steps_total", stats.get("steps", 0))
    m.inc("serve_decode_steps_total", stats.get("decode_steps", 0))
    m.inc("serve_prompt_tokens_total",
          stats.get("prompt_tokens_total", 0))
    m.inc("serve_prefill_tokens_computed_total",
          stats.get("prefill_tokens_computed", 0))
    m.inc("serve_prefix_hit_tokens_total",
          stats.get("prefix_hit_tokens", 0))
    m.inc("serve_preemptions_total", stats.get("preemptions", 0))
    m.inc("serve_retries_total", stats.get("retries", 0))
    for k in ("cancelled", "deadline_expired", "rejected"):
        m.inc(f"serve_{k}_total", stats.get(k, 0))
    for rung, n in enumerate(stats.get("rung_steps") or []):
        m.inc("serve_rung_steps_total", n, rung=rung)
    m.inc("serve_spec_drafted_tokens_total",
          stats.get("spec_drafted_tokens", 0))
    m.inc("serve_spec_accepted_tokens_total",
          stats.get("spec_accepted_tokens", 0))
    m.set("serve_wall_seconds", stats.get("wall_s", 0.0))
    m.set("serve_tokens_per_sec", stats.get("tokens_per_sec", 0.0))
    m.set("serve_pool_occupancy_peak", stats.get("page_util_max", 0.0))
    m.set("serve_pool_occupancy_mean", stats.get("page_util_mean", 0.0))
    pt = stats.get("prompt_tokens_total", 0)
    m.set("serve_prefix_hit_rate",
          stats.get("prefix_hit_tokens", 0) / pt if pt else 0.0)
    m.set("serve_spec_acceptance", stats.get("spec_acceptance", 0.0))
    m.set("serve_steps_per_decode_token",
          stats.get("steps_per_decode_token", 0.0))
    m.set("serve_degradation_rung_max",
          stats.get("degradation_rung_max", 0))
    for prog, n in (stats.get("compile_counts") or {}).items():
        m.counter_set("serve_compiled_programs", n, program=prog)
    # engine-lifetime prefix-cache counters track their own totals
    for k, v in (stats.get("cache") or {}).items():
        if isinstance(v, (int, float)):
            m.counter_set(f"serve_prefix_cache_{k}_total", v)
    # host-tier counters/gauges (hierarchical prefix cache,
    # serve/host_tier.py) — block absent when the tier is unarmed;
    # the store tracks its own lifetime totals, so counter_set
    ht = stats.get("host_tier") or {}
    for k in ("spills", "reloads", "hits", "misses", "evictions"):
        if k in ht:
            m.counter_set(f"serve_host_tier_{k}_total", ht[k])
    if ht:
        m.set("serve_host_tier_bytes", float(ht.get("bytes", 0)))
        m.set("serve_host_tier_occupancy",
              float(ht.get("occupancy", 0.0)))
        m.set("serve_host_tier_pages", ht.get("pages", 0))
        m.counter_set("serve_host_tier_reload_pages_total",
                      ht.get("reload_pages", 0))
        m.counter_set("serve_host_tier_recompute_chosen_total",
                      ht.get("recompute_chosen", 0))
    # adapter-pool counters/gauges (multi-tenant LoRA serving,
    # serve/adapters.py) — block absent when the pool is unarmed
    ad = stats.get("adapter_pool") or {}
    for k in ("hits", "misses", "loads", "evictions", "releases",
              "blocked_admissions", "blocked_steps"):
        if k in ad:
            m.counter_set(f"serve_adapter_{k}_total", ad[k])
    if ad:
        m.set("serve_adapter_pool_occupancy",
              float(ad.get("occupancy", 0.0)))
        m.set("serve_adapter_resident_tenants",
              ad.get("resident_tenants", 0))
        m.set("serve_adapter_registered_tenants",
              ad.get("registered_tenants", 0))
    return m


def train_metrics(stats: dict,
                  registry: Optional[MetricsRegistry] = None
                  ) -> MetricsRegistry:
    """Fold one fit() run's last_train_stats into a MetricsRegistry —
    the source train_report renders from and train_bench exports."""
    m = registry if registry is not None else MetricsRegistry()
    if not stats:
        return m
    m.inc("train_dispatches_total", stats.get("dispatches", 0))
    m.set("train_dispatch_depth", stats.get("dispatch_depth", 0))
    m.set("train_max_in_flight", stats.get("max_in_flight", 0))
    m.set("train_in_flight_at_exit", stats.get("in_flight_at_exit", 0))
    m.set("train_dispatch_gap_seconds_mean",
          stats.get("dispatch_gap_s_mean", 0.0))
    m.set("train_dispatch_gap_seconds_p50",
          stats.get("dispatch_gap_s_p50", 0.0))
    m.set("train_dispatch_gap_seconds_max",
          stats.get("dispatch_gap_s_max", 0.0))
    m.set("train_fetch_wait_seconds_total",
          stats.get("fetch_wait_s_total", 0.0))
    m.set("train_fetch_wait_seconds_max",
          stats.get("fetch_wait_s_max", 0.0))
    m.set("train_data_parallel", stats.get("data_parallel", 1))
    m.set("train_est_comm_hidden", stats.get("est_comm_hidden", 0.0))
    b = stats.get("grad_buckets") or {}
    m.set("train_grad_buckets", b.get("count", 0))
    m.set("train_grad_bucket_mb", b.get("bucket_mb", 0.0))
    for i, nbytes in enumerate(b.get("bytes", []) or []):
        m.set("train_grad_bucket_bytes", nbytes, bucket=i)
    return m
