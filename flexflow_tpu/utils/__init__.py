"""Utilities: profiling, logging."""
