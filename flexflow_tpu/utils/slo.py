"""SLO burn-rate monitoring over exported metrics (docs/observability.md
"SLO burn-rate monitor"; rendered by tools/slo_report.py).

A p99 gauge tells you the tier is slow NOW; an error-budget burn rate
tells you whether the month's SLO is in danger and how fast — the
number a pager should fire on (the multi-window, multi-burn-rate
alerting discipline of the Google SRE workbook). This module is that
control loop for the serving tier's ``slo_ttft_ms`` / ``slo_tpot_ms``
targets:

  * the ReplicaPool exports the error-budget counters as it finalizes
    requests (``serve_slo_requests_total`` — every finalized request
    except user abandons — and ``serve_slo_violations_total``, labeled
    by the bound that burned: ``{slo="ttft"|"tpot"|"outcome"}``);
  * :class:`SLOBurnMonitor` ticks on the pool's deterministic virtual
    clock and computes, per tick, the windowed error rate over a FAST
    window (catches a sharp outage in minutes) and a SLOW window
    (catches a lingering brownout a fast window forgives), each
    divided by the error budget into a BURN RATE — burn 1.0 spends the
    budget exactly at period end, burn 14.4 spends a 30-day budget in
    2 days;
  * an alert FIRES when both windows burn past their thresholds
    (the two-window AND is what keeps a single bad request from
    paging) and CLEARS when both drop back under; every transition is
    recorded in ``monitor.events`` (virtual-time, replayable at one
    seed) and emitted as telemetry — ``slo_alert_fire`` /
    ``slo_alert_clear`` instants plus one complete ``slo_alert`` span
    per episode on the ``(serve, slo)`` track;
  * every tick publishes ``slo_burn_rate{window="fast"|"slow"[,slo]}``
    and ``slo_budget_remaining`` gauges into the same registry, so a
    /metrics scrape carries the burn state alongside the latency
    histograms it derives from.

The monitor reads ONLY exported registry values (the autoscaler's
gauges-only rule, extended to the error-budget counters): a decision
is a pure function of (exported metrics at tick times, monitor state),
which is exactly what makes ``tools/slo_report.py --smoke`` able to
gate that two monitors replaying one counter history produce
bit-identical alert transitions.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .telemetry import MetricsRegistry, Telemetry

__all__ = ["SLOBurnMonitor"]

_SLO_TRACK = ("serve", "slo")

# violation labels the pool exports (serve/router.py _finalize):
# which SLO bound a violating request burned
SLO_DIMS = ("ttft", "tpot", "outcome")


class SLOBurnMonitor:
    """Multi-window error-budget burn-rate monitor.

    ``error_budget`` is the tolerated violation fraction (0.01 = a
    99% SLO). ``fast_burn`` / ``slow_burn`` default to the SRE-workbook
    page thresholds (14.4x / 6x — budget gone in ~2 days / ~5 days at
    a 30-day period); both windows must burn past threshold for the
    alert to fire, and both must recover for it to clear. All times
    are whatever clock the caller ticks ``observe`` on — the
    ReplicaPool uses its deterministic virtual clock, a wall-clock
    deployment would tick wall seconds; the monitor never reads a
    clock itself (except to stamp telemetry span walls), which is what
    keeps replays exact."""

    def __init__(self, registry: MetricsRegistry, *,
                 error_budget: float = 0.01,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 fast_burn: float = 14.4,
                 slow_burn: float = 6.0,
                 interval_s: float = 60.0,
                 telemetry: Optional[Telemetry] = None,
                 slo: Optional[dict] = None):
        if not (0.0 < error_budget <= 1.0):
            raise ValueError(
                f"error_budget must be in (0, 1], got {error_budget}")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                f"need 0 < fast_window_s <= slow_window_s, got "
                f"{fast_window_s}/{slow_window_s}")
        if fast_burn <= 0 or slow_burn <= 0:
            raise ValueError(
                f"burn thresholds must be > 0, got "
                f"{fast_burn}/{slow_burn}")
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.registry = registry
        self.error_budget = float(error_budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.interval_s = float(interval_s)
        self.telemetry = telemetry
        self.slo = dict(slo or {})
        # counter-history samples: (t, total, viol, {dim: viol_dim}).
        # Bounded: everything strictly older than the slow window is
        # pruned (one pre-window sample survives as the baseline).
        self._samples: deque = deque()
        self.state = "ok"
        self.episodes = 0
        self._fire_wall: Optional[float] = None
        self._fire_t: Optional[float] = None
        self.events: List[dict] = []

    @classmethod
    def from_config(cls, config, registry: MetricsRegistry,
                    **kw) -> "SLOBurnMonitor":
        """Budget from FFConfig.slo_error_budget, SLO targets from the
        --slo-ttft-ms/--slo-tpot-ms flags (for the report header)."""
        kw.setdefault("error_budget",
                      float(getattr(config, "slo_error_budget", 0.01)))
        kw.setdefault("slo", {
            "ttft_s": float(getattr(config, "slo_ttft_ms", 0.0)) / 1e3,
            "tpot_s": float(getattr(config, "slo_tpot_ms", 0.0)) / 1e3})
        return cls(registry, **kw)

    # ---------------- the windowed burn math ---------------------------
    def _read(self) -> Tuple[float, float, Dict[str, float]]:
        m = self.registry
        return (m.counter("serve_slo_requests_total"),
                m.counter("serve_slo_violations_total"),
                {d: m.counter("serve_slo_violations_total", slo=d)
                 for d in SLO_DIMS})

    def _baseline(self, t_now: float, window_s: float):
        """Latest sample at or before the window start (the FIRST
        sample when history is shorter than the window — the burn then
        covers all available history, the conservative read)."""
        base = self._samples[0]
        for s in self._samples:
            if s[0] <= t_now - window_s:
                base = s
            else:
                break
        return base

    def _burn(self, t_now: float, window_s: float,
              dim: Optional[str] = None) -> float:
        """Windowed violation fraction over the error budget. No
        requests in the window = burn 0 (an idle tier spends no
        budget)."""
        now = self._samples[-1]
        base = self._baseline(t_now, window_s)
        total = now[1] - base[1]
        if total <= 0:
            return 0.0
        if dim is None:
            viol = now[2] - base[2]
        else:
            viol = now[3][dim] - base[3][dim]
        return (viol / total) / self.error_budget

    # ---------------- the control tick ----------------------------------
    def observe(self, t_now: float) -> Optional[dict]:
        """One tick: sample the exported counters, publish the burn
        gauges, and fire/clear the alert. Returns the transition event
        when one happened (also appended to ``events``), else None."""
        t_now = float(t_now)
        total, viol, dims = self._read()
        self._samples.append((t_now, total, viol, dims))
        # prune past the slow window, keeping one baseline sample
        while len(self._samples) >= 2 \
                and self._samples[1][0] <= t_now - self.slow_window_s:
            self._samples.popleft()
        fast = self._burn(t_now, self.fast_window_s)
        slow = self._burn(t_now, self.slow_window_s)
        remaining = (1.0 - viol / (self.error_budget * total)
                     if total > 0 else 1.0)
        m = self.registry
        m.set("slo_burn_rate", fast, window="fast")
        m.set("slo_burn_rate", slow, window="slow")
        for d in SLO_DIMS:
            m.set("slo_burn_rate", self._burn(t_now, self.fast_window_s,
                                              d),
                  window="fast", slo=d)
        m.set("slo_budget_remaining", remaining)
        m.set("slo_error_budget", self.error_budget)
        m.set("slo_alert_firing", 1.0 if self.state == "firing" else 0.0)
        firing = fast >= self.fast_burn and slow >= self.slow_burn
        event = None
        if firing and self.state == "ok":
            self.state = "firing"
            self.episodes += 1
            self._fire_t = t_now
            self._fire_wall = time.perf_counter()
            event = {"t": t_now, "state": "firing",
                     "episode": self.episodes, "burn_fast": fast,
                     "burn_slow": slow, "budget_remaining": remaining}
            if self.telemetry is not None and self.telemetry.enabled:
                self.telemetry.instant(
                    _SLO_TRACK, "slo_alert_fire",
                    args={k: v for k, v in event.items()})
            m.inc("slo_alerts_total", direction="fire")
            m.set("slo_alert_firing", 1.0)
        elif not firing and self.state == "firing":
            self.state = "ok"
            event = {"t": t_now, "state": "ok",
                     "episode": self.episodes, "burn_fast": fast,
                     "burn_slow": slow, "budget_remaining": remaining}
            self._close_episode(t_now, event)
            m.inc("slo_alerts_total", direction="clear")
            m.set("slo_alert_firing", 0.0)
        if event is not None:
            self.events.append(event)
        return event

    def _close_episode(self, t_now: float, event: dict) -> None:
        """Emit the episode's telemetry: a clear instant plus ONE
        complete ``slo_alert`` span covering the episode's WALL
        interval (the trace clock is wall time; the virtual fire/clear
        times ride in args, the autoscaler-span convention)."""
        tel = self.telemetry
        if tel is not None and tel.enabled \
                and self._fire_wall is not None:
            now_wall = time.perf_counter()
            tel.instant(_SLO_TRACK, "slo_alert_clear",
                        args={k: v for k, v in event.items()})
            tel.span(_SLO_TRACK, "slo_alert", self._fire_wall,
                     now_wall,
                     args={"episode": self.episodes,
                           "t_fire": self._fire_t, "t_clear": t_now})
        self._fire_wall = None
        self._fire_t = None

    def finish(self, t_now: float) -> None:
        """Close a still-burning episode's SPAN at drain (the alert
        state itself does not transition — the tier ended the run in
        violation, and the events list says so honestly)."""
        if self.state == "firing":
            self._close_episode(
                float(t_now),
                {"t": float(t_now), "state": "end_firing",
                 "episode": self.episodes})

    # ---------------- reporting -----------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready monitor state for tools/slo_report.py: config,
        current burn gauges, alert state and the transition history."""
        m = self.registry
        return {
            "error_budget": self.error_budget,
            "slo": dict(self.slo),
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn_threshold": self.fast_burn,
            "slow_burn_threshold": self.slow_burn,
            "interval_s": self.interval_s,
            "state": self.state,
            "episodes": self.episodes,
            "burn_fast": m.gauge("slo_burn_rate", window="fast"),
            "burn_slow": m.gauge("slo_burn_rate", window="slow"),
            "budget_remaining": m.gauge("slo_budget_remaining", 1.0),
            "requests": m.counter("serve_slo_requests_total"),
            "violations": m.counter("serve_slo_violations_total"),
            "violations_by_slo": {
                d: m.counter("serve_slo_violations_total", slo=d)
                for d in SLO_DIMS},
            "events": list(self.events),
        }
