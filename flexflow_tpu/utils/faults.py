"""Deterministic fault injection — the chaos-testing substrate.

A production replica lives with preempted TPU VMs, transient device
errors, client disconnects and kill -9 mid-checkpoint; none of those
appear in a clean test run unless something injects them. This module
is that something: subsystems mark their failure-prone boundaries with
named SITES (`fire("serve.mixed")` before a program dispatch,
`fire("ckpt.commit")` between a checkpoint's temp write and its atomic
promote, `level("serve.page_pressure")` when the scheduler sizes a
step), and a :class:`FaultInjector` configured from a compact spec
string decides — deterministically — which invocation of which site
fails, and how.

Determinism is the whole point: a chaos test that fails must replay
bit-for-bit from its spec + seed, so every trigger is either an
explicit hit index or a Bernoulli draw from a per-site stream seeded by
(seed, site name). No global RNG, no wall clock.

Spec grammar (semicolon-separated clauses)::

    site:kind[:value]@hits[;...]

    kind   transient  raise TransientError   (retryable — serve retries)
           fatal      raise InjectedFault    (not retryable)
           kill       raise SimulatedKill    (BaseException: simulated
                                              process death — ordinary
                                              `except Exception`
                                              recovery must NOT see it)
           exhaust    no raise; `level(site)` reports `value` (a
                      pressure magnitude, e.g. the fraction of the KV
                      page pool to hide from the scheduler)
    hits   comma-separated triggers, matched against the site's
           1-based invocation counter:
             7      the 7th call
             3-9    calls 3..9 inclusive
             4+     call 4 and every call after
             %5     every 5th call
             ~0.2   each call independently with p=0.2 (seeded)

Example — the CI chaos gate's spec::

    serve.mixed:transient@2,5;serve.page_pressure:exhaust:0.6@3-10

Sites in the tree today:
  serve.mixed / serve.prefill / serve.decode   engine program dispatch
  serve.page_pressure                          scheduler step sizing
  ckpt.commit                                  checkpoint promote
  loader.commit                                data-loader state promote

The default injector is process-global and EMPTY (every call is a
cheap dict miss); configure it via the ``FLEXFLOW_TPU_FAULTS`` env
var, ``FFConfig.fault_spec`` / ``--fault-spec`` (the serve engine
builds a config-scoped injector), or the :func:`active` context
manager in tests.
"""

from __future__ import annotations

import hashlib
import os
import random
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple


class TransientError(RuntimeError):
    """A retryable injected failure (the analog of a one-off device /
    tunnel error). Subsystems with a retry policy (the serve engine's
    dispatch wrapper) absorb these up to their retry budget."""


class InjectedFault(RuntimeError):
    """A non-retryable injected failure: recovery paths must fail the
    in-flight work and leave the subsystem serviceable."""


class SimulatedKill(BaseException):
    """Simulated process death (kill -9 at a marked point). Derives
    from BaseException so that `except Exception` recovery code —
    which a real SIGKILL would never run — cannot observe it; only the
    test harness that staged the kill catches it."""


class _Trigger:
    """One hits-expression, matched against a 1-based call counter."""

    __slots__ = ("kind", "a", "b", "p")

    def __init__(self, expr: str):
        expr = expr.strip()
        self.p = None
        if expr.startswith("~"):
            self.kind = "prob"
            self.p = float(expr[1:])
            if not 0.0 <= self.p <= 1.0:
                raise ValueError(f"probability out of [0,1]: {expr!r}")
        elif expr.startswith("%"):
            self.kind = "every"
            self.a = int(expr[1:])
            if self.a < 1:
                raise ValueError(f"%k needs k >= 1: {expr!r}")
        elif expr.endswith("+"):
            self.kind = "from"
            self.a = int(expr[:-1])
        elif "-" in expr:
            lo, hi = expr.split("-", 1)
            self.kind = "range"
            self.a, self.b = int(lo), int(hi)
            if self.a > self.b:
                raise ValueError(f"empty range: {expr!r}")
        else:
            self.kind = "one"
            self.a = int(expr)
        if self.kind in ("one", "from", "range") and self.a < 1:
            raise ValueError(f"hit indices are 1-based: {expr!r}")

    def matches(self, n: int, rng: Optional[random.Random]) -> bool:
        if self.kind == "one":
            return n == self.a
        if self.kind == "range":
            return self.a <= n <= self.b
        if self.kind == "from":
            return n >= self.a
        if self.kind == "every":
            return n % self.a == 0
        return rng.random() < self.p  # prob: one draw per call


class FaultClause:
    """site:kind[:value]@hits — one parsed clause."""

    __slots__ = ("site", "kind", "value", "triggers")

    KINDS = ("transient", "fatal", "kill", "exhaust")

    def __init__(self, text: str):
        head, _, hits = text.partition("@")
        if not hits:
            raise ValueError(f"clause {text!r} has no @hits part")
        parts = head.split(":")
        if len(parts) < 2:
            raise ValueError(f"clause {text!r} has no kind")
        self.site = parts[0].strip()
        self.kind = parts[1].strip()
        if self.kind not in self.KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} in {text!r} "
                f"(one of {self.KINDS})")
        self.value = float(parts[2]) if len(parts) > 2 else 1.0
        self.triggers = [_Trigger(h) for h in hits.split(",")]

    def matches(self, n: int, rng: Optional[random.Random]) -> bool:
        return any(t.matches(n, rng) for t in self.triggers)


class FaultSpec:
    """Parsed spec string: clauses grouped by site."""

    def __init__(self, text: str = ""):
        self.text = text or ""
        self.by_site: Dict[str, List[FaultClause]] = {}
        for part in self.text.split(";"):
            part = part.strip()
            if not part:
                continue
            cl = FaultClause(part)
            self.by_site.setdefault(cl.site, []).append(cl)

    def __bool__(self) -> bool:
        return bool(self.by_site)


class FaultInjector:
    """Per-site invocation counters + the spec's verdicts.

    `fire(site)` counts an invocation and raises if a raise-kind clause
    matches; `level(site)` counts an invocation and returns the largest
    matching exhaust clause's value (0.0 when none). One counter per
    site regardless of kind, so a spec's hit indices mean "the Nth time
    this site was reached", full stop."""

    def __init__(self, spec: Optional[str] = None, seed: int = 0):
        self.spec = spec if isinstance(spec, FaultSpec) \
            else FaultSpec(spec or "")
        self.seed = int(seed)
        self._count: Dict[str, int] = {}
        self._rng: Dict[str, random.Random] = {}
        # observability: what actually fired (site -> kind -> times)
        self.fired: Dict[str, Dict[str, int]] = {}

    def _site_rng(self, site: str) -> random.Random:
        rng = self._rng.get(site)
        if rng is None:
            h = hashlib.sha256(site.encode()).digest()
            rng = random.Random(self.seed ^ int.from_bytes(h[:8], "big"))
            self._rng[site] = rng
        return rng

    def _record(self, site: str, kind: str) -> None:
        d = self.fired.setdefault(site, {})
        d[kind] = d.get(kind, 0) + 1

    def hits(self, site: str) -> int:
        return self._count.get(site, 0)

    def fire(self, site: str) -> None:
        """Mark one invocation of a raise-style site. No-op (a dict
        miss) unless a clause targets the site and its trigger matches
        this invocation index."""
        clauses = self.spec.by_site.get(site)
        if not clauses:
            return
        n = self._count.get(site, 0) + 1
        self._count[site] = n
        rng = self._site_rng(site)
        for cl in clauses:
            if cl.kind == "exhaust" or not cl.matches(n, rng):
                continue
            self._record(site, cl.kind)
            if cl.kind == "transient":
                raise TransientError(
                    f"injected transient fault at {site} (hit {n})")
            if cl.kind == "fatal":
                raise InjectedFault(
                    f"injected fatal fault at {site} (hit {n})")
            raise SimulatedKill(f"injected kill at {site} (hit {n})")

    def level(self, site: str) -> float:
        """Mark one invocation of a pressure-style site; returns the
        max matching exhaust magnitude (0.0 = no pressure)."""
        clauses = self.spec.by_site.get(site)
        if not clauses:
            return 0.0
        n = self._count.get(site, 0) + 1
        self._count[site] = n
        rng = self._site_rng(site)
        lv = 0.0
        for cl in clauses:
            if cl.kind == "exhaust" and cl.matches(n, rng):
                lv = max(lv, cl.value)
        if lv > 0.0:
            self._record(site, "exhaust")
        return lv

    def reset(self) -> None:
        self._count.clear()
        self._rng.clear()
        self.fired.clear()


# ---------------- process-global default ------------------------------
_DEFAULT: Optional[FaultInjector] = None


def default_injector() -> FaultInjector:
    """The process-global injector: empty unless FLEXFLOW_TPU_FAULTS is
    set (so production code paths pay one dict miss per site)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = FaultInjector(
            os.environ.get("FLEXFLOW_TPU_FAULTS", ""),
            seed=int(os.environ.get("FLEXFLOW_TPU_FAULT_SEED", "0")))
    return _DEFAULT


def injector_for(config=None) -> FaultInjector:
    """The injector a subsystem should use: a config-scoped one when
    `config.fault_spec` is set (each engine/search gets its own
    counters — reproducible per object), else the process default."""
    spec = getattr(config, "fault_spec", None) if config is not None \
        else None
    if spec:
        return FaultInjector(spec, seed=int(getattr(config, "seed", 0)))
    return default_injector()


def fire(site: str) -> None:
    """Module-level convenience for subsystems without a config in
    reach (checkpoint promote, loader state commit)."""
    default_injector().fire(site)


@contextmanager
def active(spec: str, seed: int = 0):
    """Temporarily install a spec as the process-global injector (the
    test idiom: `with faults.active("ckpt.commit:kill@1"): ...`).
    Yields the injector so the test can assert on `.fired`."""
    global _DEFAULT
    prev = _DEFAULT
    inj = FaultInjector(spec, seed=seed)
    _DEFAULT = inj
    try:
        yield inj
    finally:
        _DEFAULT = prev
