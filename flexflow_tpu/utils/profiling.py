"""Profiling / tracing.

Reference aux subsystems (SURVEY.md section 5): Legion execution tracing
(begin/end_trace — already implicit in XLA's trace-once-replay jit),
per-op `--profiling` cudaEvent prints, and the simulator's DOT taskgraph
export (in search/simulator.py). This module adds the TPU-native pieces:
jax.profiler traces and a per-op analytic profile table.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/flexflow_tpu_trace"):
    """Capture an XLA/TPU profiler trace viewable in TensorBoard
    (jax.profiler; the analog of Legion's -lg:prof)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def op_profile(model, peak_flops: Optional[float] = None) -> str:
    """Analytic per-op table: flops, bytes, weight bytes, est. intensity.

    The analog of the reference's per-op `[Measure Linear] ...` prints
    (linear.cu:1063-1072) without needing a search run.
    """
    lines = [f"{'op':28s} {'type':18s} {'GFLOPs':>10s} {'MB moved':>10s} "
             f"{'MB weights':>11s} {'intensity':>10s}"]
    total_f = total_b = 0.0
    for op in model.ops:
        f = op.flops()
        b = op.bytes_accessed()
        w = op.weight_bytes()
        total_f += f
        total_b += b
        inten = f / b if b else 0.0
        lines.append(f"{op.name:28s} {op.op_type:18s} {f/1e9:>10.3f} "
                     f"{b/1e6:>10.2f} {w/1e6:>11.2f} {inten:>10.1f}")
    lines.append(f"{'TOTAL':28s} {'':18s} {total_f/1e9:>10.3f} "
                 f"{total_b/1e6:>10.2f}")
    if peak_flops:
        lines.append(f"ideal step time at {peak_flops/1e12:.0f} TFLOP/s: "
                     f"{3*total_f/peak_flops*1e3:.2f} ms (fwd+bwd)")
    return "\n".join(lines)


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (no numpy dep for a
    report string)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def serve_percentiles(stats: dict, qs=(50, 99)) -> dict:
    """Per-token decode latency percentiles (seconds) from
    ServeEngine.last_stats: each decode step's wall time divided over
    the tokens that step produced — the batched-decode amortization IS
    the per-token number that matters under continuous batching. The
    one definition serve_report and tools/serve_bench.py both use."""
    per_tok = sorted(
        t / w for t, w in zip(stats.get("decode_step_times_s", []),
                              stats.get("decode_widths", [])) if w > 0)
    return {q: _pct(per_tok, q) for q in qs}


def serve_report(stats: dict) -> str:
    """Render ServeEngine.last_stats as the serving analog of
    op_profile: a per-request latency table plus aggregate
    tokens/sec and per-token latency percentiles. Per-token latency is
    each decode step's wall time divided over the tokens that step
    produced (the batched-decode amortization IS the number that
    matters for continuous batching)."""
    lines = [f"{'rid':>4s} {'prompt':>7s} {'new':>5s} {'ttft ms':>9s} "
             f"{'latency ms':>11s} {'tok/s':>8s}  {'outcome':s}"]
    for r in stats.get("requests", []):
        # cancelled/expired/rejected requests may never have reached
        # first token (ttft None) or termination stamps (latency None)
        lat = r["latency_s"]
        ttft = r["ttft_s"]
        tps = r["new_tokens"] / lat if lat else 0.0
        outcome = r.get("outcome", "completed")
        lines.append(
            f"{r['rid']:>4d} {r['prompt_tokens']:>7d} "
            f"{r['new_tokens']:>5d} "
            + (f"{ttft*1e3:>9.2f} " if ttft is not None else f"{'-':>9s} ")
            + (f"{lat*1e3:>11.2f} " if lat is not None else f"{'-':>11s} ")
            + f"{tps:>8.1f}"
            + (f"  {outcome}" if outcome != "completed" else ""))
    pct = serve_percentiles(stats)
    lines.append(
        f"total: {stats.get('total_new_tokens', 0)} tokens in "
        f"{stats.get('wall_s', 0.0)*1e3:.1f} ms "
        f"({stats.get('tokens_per_sec', 0.0):.1f} tok/s, "
        f"{stats.get('decode_steps', 0)} decode steps)")
    if any(pct.values()):
        lines.append(
            f"per-token decode latency: p50={pct[50]*1e3:.3f} ms "
            f"p99={pct[99]*1e3:.3f} ms")
    # prefix cache / chunked prefill / preemption instrumentation
    # (absent from pre-v2 stats dicts — every line is key-guarded)
    pt = stats.get("prompt_tokens_total")
    if pt is not None:
        comp = stats.get("prefill_tokens_computed", 0)
        hit = stats.get("prefix_hit_tokens", 0)
        red = pt / comp if comp else float("inf")
        lines.append(
            f"prefill: computed {comp} of {pt} prompt tokens "
            f"({hit} prefix-cache hits, {red:.2f}x reduction)")
    # speculative decoding: drafted/accepted and the per-sequence
    # steps-per-token (1.0 = sequential decode; lower = accepted
    # drafts advanced sequences several tokens per dispatched step)
    drafted = stats.get("spec_drafted_tokens")
    if drafted is not None and stats.get("spec_tokens", 0) > 0:
        acc = stats.get("spec_accepted_tokens", 0)
        rate = stats.get("spec_acceptance", 0.0)
        spt = stats.get("steps_per_decode_token", 0.0)
        lines.append(
            f"speculation: drafted {drafted}, accepted {acc} "
            f"({rate:.1%} acceptance), "
            f"{spt:.2f} steps/token")
    # robustness: aborts, retried dispatches, degradation-ladder climb
    # (absent from pre-robustness stats dicts — key-guarded like the
    # rest)
    if any(stats.get(k) for k in ("cancelled", "deadline_expired",
                                  "rejected", "retries",
                                  "degradation_rung_max")):
        rungs = stats.get("rung_steps")
        lines.append(
            f"robustness: {stats.get('cancelled', 0)} cancelled, "
            f"{stats.get('deadline_expired', 0)} deadline-expired, "
            f"{stats.get('rejected', 0)} rejected, "
            f"{stats.get('retries', 0)} retried dispatches, "
            f"degradation rung max "
            f"{stats.get('degradation_rung_max', 0)}"
            + (f" (steps/rung {rungs}, "
               f"{stats.get('spec_shed_steps', 0)} spec sheds)"
               if rungs else ""))
    if "preemptions" in stats or "page_util_mean" in stats:
        lines.append(
            f"pages: utilization mean={stats.get('page_util_mean', 0.0):.1%}"
            f" max={stats.get('page_util_max', 0.0):.1%}, "
            f"{stats.get('preemptions', 0)} preemptions")
    cache = stats.get("cache")
    if cache:
        lines.append(
            f"prefix cache (engine lifetime): "
            f"{cache.get('prefix_hit_pages', 0)} page hits / "
            f"{cache.get('pages_committed', 0)} committed, "
            f"{cache.get('shared_attaches', 0)} shared attaches "
            f"(max refs {cache.get('max_page_refs', 0)}), "
            f"{cache.get('prefix_evictions', 0)} evictions, "
            f"{cache.get('rollback_pages', 0)} rolled-back pages")
    # KV pool: storage format + itemsize-derived byte accounting and
    # the quantized-capacity multiplier (serve/kv_cache.pool_report);
    # absent from pre-quantization stats dicts — key-guarded
    pool = stats.get("kv_pool")
    if pool:
        lines.append(
            f"kv pool: {pool.get('kv_dtype', 'float32')} pages, "
            f"{pool.get('bytes_per_page', 0)} B/page x "
            f"{pool.get('effective_pages', 0)} effective pages "
            f"({pool.get('pool_bytes', 0) / 2**20:.2f} MiB), "
            f"peak occupancy {pool.get('occupancy', 0.0):.1%}, "
            f"{pool.get('page_ratio_vs_f32', 1.0):.2f}x pages/byte "
            f"vs f32 ({pool.get('pages_saved_vs_f32', 0)} pages saved)")
        dp = pool.get("attn_dispatch_passes")
        if dp:
            red = dp["v1"] / dp["v2"] if dp.get("v2") else 0.0
            lines.append(
                f"ragged kernel v2: block_kv="
                f"{pool.get('attn_block_kv', 0)} tokens, "
                f"{dp['v2']} grid steps vs {dp['v1']} at v1 per-page "
                f"dispatch ({red:.1f}x fewer)")
    # tensor-parallel sharding block (ServeEngine._sharding_stats;
    # None / absent on single-device engines)
    sh = stats.get("sharding")
    if sh:
        lines.append(
            f"sharding: mesh {sh.get('mesh')}, "
            f"{sh.get('heads_per_device', 0)} heads/device, "
            f"kv pool {sh.get('kv_pool_device_bytes', 0) / 2**20:.2f} "
            f"MiB/device, "
            f"~{sh.get('collective_bytes_per_step', 0) / 2**20:.2f} "
            f"MiB collective payload/step")
    cc = stats.get("compile_counts")
    if cc:
        progs = " ".join(f"{k}={v}" for k, v in cc.items() if v)
        lines.append(f"compiled programs: {progs or 'none'}")
    return "\n".join(lines)


def search_report(stats: dict) -> str:
    """Render one strategy search's instrumentation (optimize stashes
    it on model.search_stats; tools/search_bench.py records the same
    dict): proposals/sec, the delta-vs-full simulation split, drift
    re-syncs, op-cost cache hit rates (in-memory + the persistent
    store), and the memoized 1F1B schedule-table LRU stats."""
    lines = []
    props = stats.get("proposals", 0)
    wall = stats.get("wall_s", 0.0)
    lines.append(
        f"search: {props} proposals in {wall*1e3:.1f} ms "
        f"({stats.get('proposals_per_sec', 0.0):,.0f} proposals/s, "
        f"{stats.get('chains', 1)} chain(s))")
    full = stats.get("full_sims", 0)
    delta = stats.get("delta_sims", 0)
    total = full + delta
    if total:
        lines.append(
            f"simulations: {delta} delta / {full} full "
            f"({delta / total:.1%} delta), "
            f"{stats.get('delta_fallbacks', 0)} structural fallbacks, "
            f"{stats.get('drift_resyncs', 0)} drift re-syncs")
    mem = stats.get("cost_mem_hits", 0)
    disk = stats.get("cost_disk_hits", 0)
    comp = stats.get("cost_computes", 0)
    looked = mem + disk + comp
    if looked:
        lines.append(
            f"op-cost cache: {mem} memory + {disk} disk hits / "
            f"{comp} computes ({(mem + disk) / looked:.1%} hit rate)")
    dc = stats.get("disk_cache")
    if dc:
        lines.append(
            f"persistent store: {dc.get('entries', 0)} entries "
            f"(fingerprint {stats.get('fingerprint', '?')}), "
            f"{dc.get('hits', 0)} hits / {dc.get('misses', 0)} misses "
            f"this process")
    st = stats.get("schedule_tables")
    if st:
        lines.append(
            f"schedule tables (lru {st.get('currsize', 0)}/"
            f"{st.get('maxsize', 0)}): {st.get('hits', 0)} hits / "
            f"{st.get('misses', 0)} misses")
    return "\n".join(lines)


def train_report(stats: dict) -> str:
    """Render fit()'s async-runtime instrumentation (model.
    last_train_stats): per-step dispatch gap (host time between
    consecutive dispatches — time the device may sit idle when it
    outruns the host), fetch waits (host blocked retrieving a window
    entry — device time the host successfully hid behind later
    dispatches), the grad-sync bucket layout, and the structural
    estimate of the comm fraction the bucketed backward hides."""
    if not stats:
        return "train: no stats recorded"
    lines = [
        f"train: {stats.get('dispatches', 0)} dispatches, "
        f"window depth {stats.get('dispatch_depth', 0)} "
        f"(max in flight {stats.get('max_in_flight', 0)}, "
        f"{stats.get('in_flight_at_exit', 0)} drained at exit)"]
    lines.append(
        f"dispatch gap: mean={stats.get('dispatch_gap_s_mean', 0.0)*1e3:.3f} ms "
        f"p50={stats.get('dispatch_gap_s_p50', 0.0)*1e3:.3f} ms "
        f"max={stats.get('dispatch_gap_s_max', 0.0)*1e3:.3f} ms; "
        f"fetch wait total={stats.get('fetch_wait_s_total', 0.0)*1e3:.1f} ms "
        f"(max {stats.get('fetch_wait_s_max', 0.0)*1e3:.3f} ms)")
    b = stats.get("grad_buckets") or {}
    if b.get("count"):
        sizes = " ".join(f"{x/2**20:.2f}" for x in b.get("bytes", []))
        lines.append(
            f"grad sync: {b['count']} bucket(s) of "
            f"[{sizes}] MiB (target {b.get('bucket_mb', 0.0):g} MiB), "
            f"dp={stats.get('data_parallel', 1)}, "
            f"est. comm hidden {stats.get('est_comm_hidden', 0.0):.0%}")
    else:
        lines.append(
            f"grad sync: monolithic (grad_bucket_mb=0), "
            f"dp={stats.get('data_parallel', 1)}")
    return "\n".join(lines)


def time_train_steps(model, batch, steps: int = 20, warmup: int = 3
                     ) -> float:
    """Mean seconds per training step, with device sync via a scalar
    fetch of the last step's loss (remote tunnels do not sync on
    block_until_ready — the only reliable delimiter is a device->host
    transfer). Queues all steps before draining, so Python dispatch
    overlaps device execution exactly as in production loops."""
    for _ in range(warmup):
        m = model.train_batch(batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = model.train_batch(batch)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps


def hlo_cost(model, batch) -> dict:
    """XLA's own cost analysis of the compiled train step (flops,
    bytes accessed, per-category breakdown) — the compiled-HLO analog of
    the reference simulator's measured per-op costs (SURVEY.md section 5
    prescribes 'per-op cost extraction from compiled HLO'). Complements
    op_profile (analytic) with what XLA actually emitted after fusion.
    """
    import jax
    ex = model.executor
    batch = ex.shard_batch(batch)
    rng = jax.random.PRNGKey(0)
    # the public train_step property wraps the jitted fn to inject the
    # runtime lr scalar; lower() needs the raw jit object underneath
    ex.train_step  # ensure built
    compiled = ex._train_step.lower(model.state, batch, rng,
                                    ex._lr()).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost)
