"""Profiling / tracing.

Reference aux subsystems (SURVEY.md section 5): Legion execution tracing
(begin/end_trace — already implicit in XLA's trace-once-replay jit),
per-op `--profiling` cudaEvent prints, and the simulator's DOT taskgraph
export (in search/simulator.py). This module adds the TPU-native pieces:
jax.profiler traces and a per-op analytic profile table.
"""

from __future__ import annotations

import contextlib
import time
import warnings
from typing import Optional

from .telemetry import serve_metrics, train_metrics

DEFAULT_TRACE_DIR = "/tmp/flexflow_tpu_trace"


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None, config=None):
    """Capture an XLA/TPU profiler trace viewable in TensorBoard
    (jax.profiler; the analog of Legion's -lg:prof).

    The log dir resolves: explicit ``log_dir`` arg, then
    ``FFConfig.trace_dir`` (``--trace-dir``), then the legacy
    ``/tmp/flexflow_tpu_trace`` default — and is YIELDED, so callers
    can report where the trace landed. Degrades gracefully (one
    warning, then a no-op context) when jax.profiler tracing is
    unavailable on the backend — a remote tunnel or a jax build
    without profiler support must not crash the run it was meant to
    observe."""
    if log_dir is None:
        log_dir = getattr(config, "trace_dir", None) or DEFAULT_TRACE_DIR
    started = False
    jax = None
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # profiler absent / backend refuses traces
        warnings.warn(
            f"jax.profiler trace unavailable on this backend "
            f"({type(e).__name__}: {e}); profiling.trace is a no-op")
    try:
        yield log_dir
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                warnings.warn(
                    f"jax.profiler stop_trace failed "
                    f"({type(e).__name__}: {e}); trace in {log_dir} "
                    f"may be incomplete")


def op_profile(model, peak_flops: Optional[float] = None) -> str:
    """Analytic per-op table: flops, bytes, weight bytes, est. intensity.

    The analog of the reference's per-op `[Measure Linear] ...` prints
    (linear.cu:1063-1072) without needing a search run.
    """
    lines = [f"{'op':28s} {'type':18s} {'GFLOPs':>10s} {'MB moved':>10s} "
             f"{'MB weights':>11s} {'intensity':>10s}"]
    total_f = total_b = 0.0
    for op in model.ops:
        f = op.flops()
        b = op.bytes_accessed()
        w = op.weight_bytes()
        total_f += f
        total_b += b
        inten = f / b if b else 0.0
        lines.append(f"{op.name:28s} {op.op_type:18s} {f/1e9:>10.3f} "
                     f"{b/1e6:>10.2f} {w/1e6:>11.2f} {inten:>10.1f}")
    lines.append(f"{'TOTAL':28s} {'':18s} {total_f/1e9:>10.3f} "
                 f"{total_b/1e6:>10.2f}")
    if peak_flops:
        lines.append(f"ideal step time at {peak_flops/1e12:.0f} TFLOP/s: "
                     f"{3*total_f/peak_flops*1e3:.2f} ms (fwd+bwd)")
    return "\n".join(lines)


def serve_percentiles(stats: dict, qs=(50, 99)) -> dict:
    """Per-token decode latency (TPOT) percentiles (seconds) from
    ServeEngine.last_stats: each decode step's wall time divided over
    the tokens that step produced — the batched-decode amortization IS
    the per-token number that matters under continuous batching. Reads
    the `serve_tpot_seconds` histogram of the canonical metrics fold
    (utils/telemetry.serve_metrics), so the report string, this
    helper, and every exported snapshot share one definition —
    nearest-rank over the histogram's bounded sample window
    (MetricsRegistry.HIST_WINDOW, 4096): a run longer than the window
    quantiles its most recent samples, the bounded-memory telemetry
    contract."""
    m = serve_metrics(stats)
    return {q: m.quantile("serve_tpot_seconds", q) for q in qs}


def serve_report(stats: dict) -> str:
    """Render ServeEngine.last_stats as the serving analog of
    op_profile: a per-request latency table plus aggregate
    tokens/sec and per-token latency percentiles. Every AGGREGATE
    number below reads from the canonical metrics fold
    (utils/telemetry.serve_metrics) — the same registry the
    Prometheus/JSON exporters publish — so this string and the
    exported numbers can never drift. Per-request rows and
    config-fact blocks (kv pool geometry, sharding) render from the
    stats dict directly (they are identities, not measurements)."""
    m = serve_metrics(stats)
    lines = [f"{'rid':>4s} {'prompt':>7s} {'new':>5s} {'ttft ms':>9s} "
             f"{'latency ms':>11s} {'tok/s':>8s}  {'outcome':s}"]
    for r in stats.get("requests", []):
        # cancelled/expired/rejected requests may never have reached
        # first token (ttft None) or termination stamps (latency None)
        lat = r["latency_s"]
        ttft = r["ttft_s"]
        tps = r["new_tokens"] / lat if lat else 0.0
        outcome = r.get("outcome", "completed")
        lines.append(
            f"{r['rid']:>4d} {r['prompt_tokens']:>7d} "
            f"{r['new_tokens']:>5d} "
            + (f"{ttft*1e3:>9.2f} " if ttft is not None else f"{'-':>9s} ")
            + (f"{lat*1e3:>11.2f} " if lat is not None else f"{'-':>11s} ")
            + f"{tps:>8.1f}"
            + (f"  {outcome}" if outcome != "completed" else ""))
    p50 = m.quantile("serve_tpot_seconds", 50)
    p99 = m.quantile("serve_tpot_seconds", 99)
    lines.append(
        f"total: {m.counter('serve_tokens_generated_total'):.0f} tokens "
        f"in {m.gauge('serve_wall_seconds')*1e3:.1f} ms "
        f"({m.gauge('serve_tokens_per_sec'):.1f} tok/s, "
        f"{m.counter('serve_decode_steps_total'):.0f} decode steps)")
    if p50 or p99:
        lines.append(
            f"per-token decode latency: p50={p50*1e3:.3f} ms "
            f"p99={p99*1e3:.3f} ms")
    # prefix cache / chunked prefill / preemption instrumentation
    # (absent from pre-v2 stats dicts — every line is key-guarded)
    if stats.get("prompt_tokens_total") is not None:
        pt = m.counter("serve_prompt_tokens_total")
        comp = m.counter("serve_prefill_tokens_computed_total")
        hit = m.counter("serve_prefix_hit_tokens_total")
        red = pt / comp if comp else float("inf")
        lines.append(
            f"prefill: computed {comp:.0f} of {pt:.0f} prompt tokens "
            f"({hit:.0f} prefix-cache hits, {red:.2f}x reduction)")
    # speculative decoding: drafted/accepted and the per-sequence
    # steps-per-token (1.0 = sequential decode; lower = accepted
    # drafts advanced sequences several tokens per dispatched step)
    if stats.get("spec_drafted_tokens") is not None \
            and stats.get("spec_tokens", 0) > 0:
        lines.append(
            f"speculation: drafted "
            f"{m.counter('serve_spec_drafted_tokens_total'):.0f}, "
            f"accepted "
            f"{m.counter('serve_spec_accepted_tokens_total'):.0f} "
            f"({m.gauge('serve_spec_acceptance'):.1%} acceptance), "
            f"{m.gauge('serve_steps_per_decode_token'):.2f} steps/token")
    # robustness: aborts, retried dispatches, degradation-ladder climb
    # (absent from pre-robustness stats dicts — key-guarded like the
    # rest)
    if any(stats.get(k) for k in ("cancelled", "deadline_expired",
                                  "rejected", "retries",
                                  "degradation_rung_max")):
        rungs = stats.get("rung_steps")
        lines.append(
            f"robustness: {m.counter('serve_cancelled_total'):.0f} "
            f"cancelled, "
            f"{m.counter('serve_deadline_expired_total'):.0f} "
            f"deadline-expired, "
            f"{m.counter('serve_rejected_total'):.0f} rejected, "
            f"{m.counter('serve_retries_total'):.0f} retried "
            f"dispatches, degradation rung max "
            f"{m.gauge('serve_degradation_rung_max'):.0f}"
            + (f" (steps/rung {rungs}, "
               f"{stats.get('spec_shed_steps', 0)} spec sheds)"
               if rungs else ""))
    if "preemptions" in stats or "page_util_mean" in stats:
        lines.append(
            f"pages: utilization "
            f"mean={m.gauge('serve_pool_occupancy_mean'):.1%}"
            f" max={m.gauge('serve_pool_occupancy_peak'):.1%}, "
            f"{m.counter('serve_preemptions_total'):.0f} preemptions")
    if stats.get("cache"):
        def cc(k):
            return m.counter(f"serve_prefix_cache_{k}_total")
        lines.append(
            f"prefix cache (engine lifetime): "
            f"{cc('prefix_hit_pages'):.0f} page hits / "
            f"{cc('pages_committed'):.0f} committed, "
            f"{cc('shared_attaches'):.0f} shared attaches "
            f"(max refs {cc('max_page_refs'):.0f}), "
            f"{cc('prefix_evictions'):.0f} evictions, "
            f"{cc('rollback_pages'):.0f} rolled-back pages")
    # host tier: hierarchical prefix cache below the HBM pool
    # (serve/host_tier.py); None / absent when unarmed
    ht = stats.get("host_tier")
    if ht:
        lines.append(
            f"host tier: {ht.get('pages', 0)} pages / "
            f"{ht.get('bytes', 0) / 2**20:.2f} of "
            f"{ht.get('budget_bytes', 0) / 2**20:.2f} MiB "
            f"({ht.get('occupancy', 0.0):.1%}), "
            f"{ht.get('spills', 0)} spills, "
            f"{ht.get('reloads', 0)} reloads "
            f"({ht.get('reload_pages', 0)} pages re-imported, "
            f"{ht.get('recompute_chosen', 0)} priced to recompute), "
            f"{ht.get('evictions', 0)} host evictions")
    # KV pool: storage format + itemsize-derived byte accounting and
    # the quantized-capacity multiplier (serve/kv_cache.pool_report);
    # absent from pre-quantization stats dicts — key-guarded
    pool = stats.get("kv_pool")
    if pool:
        lines.append(
            f"kv pool: {pool.get('kv_dtype', 'float32')} pages, "
            f"{pool.get('bytes_per_page', 0)} B/page x "
            f"{pool.get('effective_pages', 0)} effective pages "
            f"({pool.get('pool_bytes', 0) / 2**20:.2f} MiB), "
            f"peak occupancy {pool.get('occupancy', 0.0):.1%}, "
            f"{pool.get('page_ratio_vs_f32', 1.0):.2f}x pages/byte "
            f"vs f32 ({pool.get('pages_saved_vs_f32', 0)} pages saved)")
        dp = pool.get("attn_dispatch_passes")
        if dp:
            red = dp["v1"] / dp["v2"] if dp.get("v2") else 0.0
            lines.append(
                f"ragged kernel v2: block_kv="
                f"{pool.get('attn_block_kv', 0)} tokens, "
                f"{dp['v2']} grid steps vs {dp['v1']} at v1 per-page "
                f"dispatch ({red:.1f}x fewer)")
    # adapter pool: multi-tenant LoRA slab residency + churn counters
    # (serve/adapters.pool_report); None / absent when unarmed
    ad = stats.get("adapter_pool")
    if ad:
        lines.append(
            f"adapter pool: rank {ad.get('rank', 0)}, "
            f"{ad.get('usable_slots', 0)} slots x "
            f"{ad.get('bytes_per_slot', 0) / 2**20:.2f} MiB "
            f"({ad.get('pool_bytes', 0) / 2**20:.2f} MiB), "
            f"{ad.get('resident_tenants', 0)}/"
            f"{ad.get('registered_tenants', 0)} tenants resident, "
            f"occupancy {ad.get('occupancy', 0.0):.1%}")
        lines.append(
            f"adapter churn: {ad.get('hits', 0)} hits / "
            f"{ad.get('misses', 0)} misses, {ad.get('loads', 0)} "
            f"loads, {ad.get('evictions', 0)} evictions, "
            f"{ad.get('blocked_admissions', 0)} blocked admissions "
            f"({ad.get('blocked_steps', 0)} stalled steps)")
    # tensor-parallel sharding block (ServeEngine._sharding_stats;
    # None / absent on single-device engines)
    sh = stats.get("sharding")
    if sh:
        lines.append(
            f"sharding: mesh {sh.get('mesh')}, "
            f"{sh.get('heads_per_device', 0)} heads/device, "
            f"kv pool {sh.get('kv_pool_device_bytes', 0) / 2**20:.2f} "
            f"MiB/device, "
            f"~{sh.get('collective_bytes_per_step', 0) / 2**20:.2f} "
            f"MiB collective payload/step")
    cc = stats.get("compile_counts")
    if cc:
        progs = " ".join(
            f"{k}={m.counter('serve_compiled_programs', program=k):.0f}"
            for k in cc if cc[k])
        lines.append(f"compiled programs: {progs or 'none'}")
    return "\n".join(lines)


def disagg_report(stats: dict, metrics=None) -> str:
    """Render a DisaggCluster.last_stats dict: the role-split serving
    A/B surface (docs/serving.md "Disaggregated serving"). Every
    latency number reads from the role-labeled metrics fold
    (utils/telemetry.serve_metrics role=...). Pass the cluster's own
    registry (`cluster.metrics`) to render exactly what it exports —
    the PR 10 no-drift rule — noting that registry is
    CLUSTER-LIFETIME (counters accumulate across generate calls, so
    the per-role lines are labeled "(lifetime)" and can legitimately
    exceed the header's per-call totals). With metrics=None the fold
    is rebuilt from the per-role stats of THIS call's dict, so every
    line describes the same run."""
    lifetime = metrics is not None
    m = metrics
    if m is None:
        from .telemetry import MetricsRegistry
        m = MetricsRegistry()
        for role, role_stats in (stats.get("roles") or {}).items():
            for st in role_stats:
                # only the role-labeled series feed the lines below
                serve_metrics(st, registry=m, role=role)
    lines = [
        f"disaggregated cluster: {stats.get('prefill_engines', 0)} "
        f"prefill + {stats.get('decode_engines', 0)} decode engines "
        f"(decode-role prefill stub {stats.get('decode_budget', 0)} "
        f"lanes)"]
    lines.append(
        f"total: {stats.get('total_new_tokens', 0)} tokens in "
        f"{stats.get('wall_s', 0.0)*1e3:.1f} ms "
        f"({stats.get('tokens_per_sec', 0.0):.1f} tok/s)")
    for role in ("prefill", "decode"):
        ttft50 = m.quantile("serve_ttft_seconds", 50, role=role)
        ttft99 = m.quantile("serve_ttft_seconds", 99, role=role)
        tpot50 = m.quantile("serve_tpot_seconds", 50, role=role)
        tpot99 = m.quantile("serve_tpot_seconds", 99, role=role)
        toks = m.counter("serve_tokens_generated_total", role=role)
        steps = m.counter("serve_engine_steps_total", role=role)
        scope = " (lifetime)" if lifetime else ""
        line = (f"{role} role{scope}: {toks:.0f} tokens / "
                f"{steps:.0f} steps, "
                f"ttft p50={ttft50*1e3:.2f} p99={ttft99*1e3:.2f} ms")
        if tpot50 or tpot99:
            line += (f", tpot p50={tpot50*1e3:.3f} "
                     f"p99={tpot99*1e3:.3f} ms")
        lines.append(line)
    h = stats.get("handoff") or {}
    if h:
        lines.append(
            f"kv handoff: {h.get('handoff_requests', 0):.0f} requests, "
            f"{h.get('handoff_pages', 0):.0f} pages / "
            f"{h.get('handoff_bytes', 0) / 2**20:.2f} MiB transferred, "
            f"{h.get('handoff_dedup_pages', 0):.0f} deduped, "
            f"{h.get('handoff_skipped', 0):.0f} skipped "
            f"(backpressure), "
            f"{h.get('handoff_seconds', 0.0)*1e3:.1f} ms on the link")
    return "\n".join(lines)


def router_report(stats: dict, metrics=None) -> str:
    """Render a ReplicaPool.last_stats dict (serve/router.py): the
    multi-replica routing surface — goodput-under-SLO, the routing
    split (affinity hits / tenant fallbacks / spills / cancels), the
    per-replica load table, and the autoscaler's decisions. Latency
    and counter lines read from the pool's exported registry when
    given (``pool.metrics`` — the PR 10 no-drift rule: the report
    renders what the autoscaler and /metrics scrapes actually see);
    clock numbers (goodput, makespan) come from the stats dict —
    they ARE the exported accounting — labeled by the run's clock
    (virtual, or wall for a ``wall_clock=True`` run: docs/serving.md
    "Wall-clock mode")."""
    clock = stats.get("clock", "virtual")
    lines = [
        f"router: policy={stats.get('policy')}, "
        f"{stats.get('replicas_start', 0)} -> "
        f"{stats.get('replicas_end', 0)} replicas "
        f"({stats.get('replicas_total', 0)} built), "
        f"{len(stats.get('requests', []))} requests in "
        f"{stats.get('makespan_s', 0.0)*1e3:.2f} {clock} ms"]
    slo_t = stats.get("slo_ttft_s")
    slo_p = stats.get("slo_tpot_s")
    lines.append(
        f"goodput-under-SLO: {stats.get('goodput_per_s', 0.0):.1f} "
        f"req/s ({stats.get('slo_ok', 0)}/"
        f"{len(stats.get('requests', []))} met "
        f"ttft<={slo_t*1e3 if slo_t else 0:.2f}ms & "
        f"tpot<={slo_p*1e3 if slo_p else 0:.3f}ms; "
        f"{stats.get('completed', 0)} completed, "
        f"{stats.get('cancelled', 0)} cancelled)")
    # the 2-D serve-mesh placement (--serve-replicas auto,
    # search/serve_place.optimize_serve_mesh): the chosen (t, r) cell,
    # its priced goodput, the best rejected neighbor cells WITH their
    # prices, and the HBM-rejected degrees — the chosen-vs-rejected
    # explain discipline applied to the pool shape
    mp = stats.get("mesh_placement")
    if mp:
        lines.append(
            f"2-D placement: t={mp['tensor_parallel']} x "
            f"r={mp['replicas']} over {mp['num_devices']} devices "
            f"(tensor dims {tuple(mp['tensor_axis_dims'])}, data dims "
            f"{tuple(mp['data_axis_dims'])}), priced goodput "
            f"{mp['goodput_per_s']:.1f} req/s")
        chosen = f"{mp['tensor_parallel']}x{mp['replicas']}"
        rej = sorted(
            ((k, c) for k, c in (mp.get("table") or {}).items()
             if k != chosen),
            key=lambda kc: -kc[1].get("goodput_per_s", 0.0))
        if rej:
            lines.append("  rejected cells: " + ", ".join(
                f"(t x r)={k} {c['goodput_per_s']:.1f} req/s, "
                f"tpot {c['tpot_s']*1e3:.3f} ms"
                for k, c in rej[:6]))
        for d in mp.get("infeasible") or []:
            lines.append(f"  infeasible: t={d['tensor']} "
                         f"({d['reason']})")
    r = stats.get("routing") or {}
    lines.append(
        f"routing: {r.get('affinity_hits', 0)} affinity hits / "
        f"{r.get('routed', 0)} routed, "
        f"{r.get('host_hits', 0)} host-tier hits, "
        f"{r.get('adapter_affinity_hits', 0)} adapter-affinity, "
        f"{r.get('fallbacks', 0)} tenant-sticky fallbacks, "
        f"{r.get('spills', 0)} load spills, "
        f"{r.get('cancels_sent', 0)} cancels")
    # the SHARED host tier (hierarchical prefix cache): one store
    # for the whole pool, reload decisions summed across replicas
    ht = stats.get("host_tier")
    if ht:
        lines.append(
            f"host tier (shared): {ht.get('pages', 0)} pages / "
            f"{ht.get('bytes', 0) / 2**20:.2f} of "
            f"{ht.get('budget_bytes', 0) / 2**20:.2f} MiB, "
            f"{ht.get('spills', 0)} spills, "
            f"{ht.get('reload_pages', 0)} pages re-imported "
            f"({ht.get('recompute_chosen', 0)} priced to recompute, "
            f"{ht.get('reload_priced_s', 0.0)*1e3:.2f} ms DMA), "
            f"{ht.get('evictions', 0)} host evictions")
    if metrics is not None:
        t50 = metrics.quantile(f"serve_router_ttft_{clock}_seconds", 50)
        t99 = metrics.quantile(f"serve_router_ttft_{clock}_seconds", 99)
        p50 = metrics.quantile(f"serve_router_tpot_{clock}_seconds", 50)
        p99 = metrics.quantile(f"serve_router_tpot_{clock}_seconds", 99)
        lines.append(
            f"{clock} latency: ttft p50={t50*1e3:.3f} "
            f"p99={t99*1e3:.3f} ms, tpot p50={p50*1e3:.4f} "
            f"p99={p99*1e3:.4f} ms")
    per = stats.get("per_replica") or []
    if per:
        lines.append(f"{'replica':>8s} {'state':>8s} {'reqs':>6s} "
                     f"{'steps':>7s} {'tokens':>7s} {'busy ms':>9s} "
                     f"{'peak occ':>9s}")
        for p in per:
            state = "live" if p.get("live") else "parked"
            lines.append(
                f"{p['replica']:>8d} {state:>8s} "
                f"{p['assigned']:>6d} {p['steps']:>7d} "
                f"{p['tokens']:>7d} "
                f"{p.get('busy_wall_s', 0.0)*1e3 if clock == 'wall' else p['busy_virtual_s']*1e3:>9.2f} "
                f"{p['peak_occupancy']:>9.1%}")
    ev = stats.get("scale_events") or []
    if ev:
        for e in ev:
            lines.append(
                f"autoscale {e['direction']} @ {e['t']*1e3:.2f} "
                f"virtual ms -> replica {e['replica']} "
                f"({e.get('reason', '')})")
    elif stats.get("scale_events") is not None:
        lines.append("autoscale: no decisions (steady)")
    # SLO error-budget burn (utils/slo.py): attainment over the
    # exported counters + the burn monitor's alert transitions
    if stats.get("slo_attainment_budget") is not None \
            and (stats.get("slo_ttft_s") or stats.get("slo_tpot_s")):
        line = (f"slo budget: attainment "
                f"{stats['slo_attainment_budget']:.2%}")
        if metrics is not None:
            line += (f", burn fast="
                     f"{metrics.gauge('slo_burn_rate', window='fast'):.2f}x "
                     f"slow="
                     f"{metrics.gauge('slo_burn_rate', window='slow'):.2f}x, "
                     f"budget remaining "
                     f"{metrics.gauge('slo_budget_remaining', 1.0):.1%}")
        lines.append(line)
        for a in stats.get("slo_alerts") or []:
            lines.append(
                f"  slo alert -> {a['state']} @ "
                f"{a['t']*1e3:.2f} virtual ms "
                f"(fast {a.get('burn_fast', 0):.1f}x, "
                f"slow {a.get('burn_slow', 0):.1f}x)")
    # pool-level latency attribution (per-request explain_request
    # folds, wall seconds): where the tier's real time went
    att = stats.get("attribution")
    if att and sum(att.values()) > 0:
        tot = sum(att.values())
        lines.append("latency attribution: " + " ".join(
            f"{c}={v / tot:.1%}" for c, v in att.items() if v > 0))
    return "\n".join(lines)


def search_report(stats: dict) -> str:
    """Render one strategy search's instrumentation (optimize stashes
    it on model.search_stats; tools/search_bench.py records the same
    dict): proposals/sec, the delta-vs-full simulation split, drift
    re-syncs, op-cost cache hit rates (in-memory + the persistent
    store), and the memoized 1F1B schedule-table LRU stats."""
    lines = []
    props = stats.get("proposals", 0)
    wall = stats.get("wall_s", 0.0)
    lines.append(
        f"search: {props} proposals in {wall*1e3:.1f} ms "
        f"({stats.get('proposals_per_sec', 0.0):,.0f} proposals/s, "
        f"{stats.get('chains', 1)} chain(s))")
    full = stats.get("full_sims", 0)
    delta = stats.get("delta_sims", 0)
    total = full + delta
    if total:
        lines.append(
            f"simulations: {delta} delta / {full} full "
            f"({delta / total:.1%} delta), "
            f"{stats.get('delta_fallbacks', 0)} structural fallbacks, "
            f"{stats.get('drift_resyncs', 0)} drift re-syncs")
    mem = stats.get("cost_mem_hits", 0)
    disk = stats.get("cost_disk_hits", 0)
    comp = stats.get("cost_computes", 0)
    looked = mem + disk + comp
    if looked:
        lines.append(
            f"op-cost cache: {mem} memory + {disk} disk hits / "
            f"{comp} computes ({(mem + disk) / looked:.1%} hit rate)")
    dc = stats.get("disk_cache")
    if dc:
        lines.append(
            f"persistent store: {dc.get('entries', 0)} entries "
            f"(fingerprint {stats.get('fingerprint', '?')}), "
            f"{dc.get('hits', 0)} hits / {dc.get('misses', 0)} misses "
            f"this process")
    st = stats.get("schedule_tables")
    if st:
        lines.append(
            f"schedule tables (lru {st.get('currsize', 0)}/"
            f"{st.get('maxsize', 0)}): {st.get('hits', 0)} hits / "
            f"{st.get('misses', 0)} misses")
    tr = stats.get("trace")
    if tr:
        # convergence diagnostics (search/trace.SearchTrace.summary):
        # acceptance by annealing phase, proposals by simulation path,
        # and the best-cost-curve tail
        phases = " ".join(
            f"{p['rate']:.1%}" for p in tr.get("acceptance_by_phase",
                                               []))
        lines.append(
            f"trace: {tr.get('accepts', 0)}/{tr.get('proposals', 0)} "
            f"accepted ({tr.get('acceptance_rate', 0.0):.1%}; by phase "
            f"{phases}), {tr.get('improvements', 0)} improvements")
        bp = tr.get("by_path") or {}
        if bp:
            lines.append("trace paths: " + ", ".join(
                f"{path} {d['proposals']} proposed / {d['accepts']} "
                f"accepted" for path, d in bp.items()))
        curve = tr.get("best_cost_curve") or []
        if curve:
            tail = curve[-5:]
            lines.append("best-cost curve (tail): " + " -> ".join(
                f"{c['cost_s']*1e3:.3f}ms@{c['iteration']}"
                for c in tail))
    sched = stats.get("schedule_trace")
    if sched:
        lines.append(
            f"schedule trace: {sched.get('path')} "
            f"({sched.get('tasks', 0)} tasks, "
            f"{sched.get('critical_tasks', 0)} on the critical path, "
            f"makespan {sched.get('makespan_s', 0.0)*1e3:.3f} ms)")
    return "\n".join(lines)


def train_report(stats: dict) -> str:
    """Render fit()'s async-runtime instrumentation (model.
    last_train_stats): per-step dispatch gap (host time between
    consecutive dispatches — time the device may sit idle when it
    outruns the host), fetch waits (host blocked retrieving a window
    entry — device time the host successfully hid behind later
    dispatches), the grad-sync bucket layout, and the structural
    estimate of the comm fraction the bucketed backward hides."""
    if not stats:
        return "train: no stats recorded"
    m = train_metrics(stats)
    lines = [
        f"train: {m.counter('train_dispatches_total'):.0f} dispatches, "
        f"window depth {m.gauge('train_dispatch_depth'):.0f} "
        f"(max in flight {m.gauge('train_max_in_flight'):.0f}, "
        f"{m.gauge('train_in_flight_at_exit'):.0f} drained at exit)"]
    lines.append(
        f"dispatch gap: "
        f"mean={m.gauge('train_dispatch_gap_seconds_mean')*1e3:.3f} ms "
        f"p50={m.gauge('train_dispatch_gap_seconds_p50')*1e3:.3f} ms "
        f"max={m.gauge('train_dispatch_gap_seconds_max')*1e3:.3f} ms; "
        f"fetch wait "
        f"total={m.gauge('train_fetch_wait_seconds_total')*1e3:.1f} ms "
        f"(max {m.gauge('train_fetch_wait_seconds_max')*1e3:.3f} ms)")
    b = stats.get("grad_buckets") or {}
    if b.get("count"):
        sizes = " ".join(f"{x/2**20:.2f}" for x in b.get("bytes", []))
        lines.append(
            f"grad sync: {m.gauge('train_grad_buckets'):.0f} bucket(s) "
            f"of [{sizes}] MiB "
            f"(target {m.gauge('train_grad_bucket_mb'):g} MiB), "
            f"dp={m.gauge('train_data_parallel'):.0f}, "
            f"est. comm hidden {m.gauge('train_est_comm_hidden'):.0%}")
    else:
        lines.append(
            f"grad sync: monolithic (grad_bucket_mb=0), "
            f"dp={m.gauge('train_data_parallel'):.0f}")
    return "\n".join(lines)


def time_train_steps(model, batch, steps: int = 20, warmup: int = 3
                     ) -> float:
    """Mean seconds per training step, with device sync via a scalar
    fetch of the last step's loss (remote tunnels do not sync on
    block_until_ready — the only reliable delimiter is a device->host
    transfer). Queues all steps before draining, so Python dispatch
    overlaps device execution exactly as in production loops."""
    for _ in range(warmup):
        m = model.train_batch(batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = model.train_batch(batch)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps


def hlo_cost(model, batch) -> dict:
    """XLA's own cost analysis of the compiled train step (flops,
    bytes accessed, per-category breakdown) — the compiled-HLO analog of
    the reference simulator's measured per-op costs (SURVEY.md section 5
    prescribes 'per-op cost extraction from compiled HLO'). Complements
    op_profile (analytic) with what XLA actually emitted after fusion.
    """
    import jax
    ex = model.executor
    batch = ex.shard_batch(batch)
    rng = jax.random.PRNGKey(0)
    # the public train_step property wraps the jitted fn to inject the
    # runtime lr scalar; lower() needs the raw jit object underneath
    ex.train_step  # ensure built
    compiled = ex._train_step.lower(model.state, batch, rng,
                                    ex._lr()).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost)
