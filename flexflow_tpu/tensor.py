"""Symbolic tensor handles for the graph builder.

The reference `Tensor` (include/tensor.h:27-73) wraps a Legion
`LogicalRegion` plus gradient region and partitions. On TPU there are no
regions: a `Tensor` here is a *symbolic* handle produced while the user
builds the graph; concrete values are JAX arrays materialized by the
executor, and gradients come from `jax.grad` instead of paired grad
regions. Partitions become sharding specs attached at compile time
(flexflow_tpu/parallel/sharding.py).
"""

from __future__ import annotations

import itertools
from typing import TYPE_CHECKING, Optional, Tuple

import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:
    from .op import Op

_uid = itertools.count()


class Tensor:
    """Symbolic N-D tensor handle.

    shape is stored outer-to-inner, NumPy order. (The reference stores
    Legion/Fortran order `adim[]` innermost-first, tensor.h:44; we keep
    NumPy order throughout and translate only in frontends that care.)
    """

    __slots__ = (
        "shape",
        "dtype",
        "owner_op",
        "owner_idx",
        "name",
        "uid",
        "is_input",
        "initial_value",
    )

    def __init__(
        self,
        shape: Tuple[int, ...],
        dtype=jnp.float32,
        owner_op: Optional["Op"] = None,
        owner_idx: int = 0,
        name: Optional[str] = None,
        is_input: bool = False,
    ):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = jnp.dtype(dtype)
        self.owner_op = owner_op
        self.owner_idx = owner_idx
        self.uid = next(_uid)
        self.name = name or f"tensor_{self.uid}"
        self.is_input = is_input
        self.initial_value: Optional[np.ndarray] = None

    @property
    def num_dims(self) -> int:
        return len(self.shape)

    @property
    def num_elements(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def size_bytes(self) -> int:
        return self.num_elements * self.dtype.itemsize

    def __repr__(self):
        prod = self.owner_op.name if self.owner_op is not None else "input"
        return f"Tensor({self.name}, shape={self.shape}, dtype={self.dtype.name}, by={prod})"


class Parameter(Tensor):
    """A trainable weight handle (reference: include/tensor.h `Parameter`).

    ``sync_type`` is kept for API compatibility (ParameterSyncType); on TPU
    gradient synchronization is always XLA collectives inserted by GSPMD.
    """

    __slots__ = ("sync_type", "initializer_name")

    def __init__(self, shape, dtype=jnp.float32, owner_op=None, name=None,
                 sync_type: str = "none", initializer_name: str = "glorot"):
        super().__init__(shape, dtype, owner_op=owner_op, name=name)
        self.sync_type = sync_type
        self.initializer_name = initializer_name
