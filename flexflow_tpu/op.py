"""Op base class and registry.

The reference `Op` (include/model.h:188-254) owns Legion index spaces,
per-worker `OpMeta*`, and implements a 7-method contract of
init/forward/backward/partitioning/cost tasks. The TPU-native contract is
much smaller because XLA supplies scheduling, autodiff supplies backward,
and GSPMD supplies partitioning:

  * ``output_shapes``  — static shape inference (replaces
    create_output_and_partition, model.cc:589-657 shape math).
  * ``weight_specs``   — declares trainable parameters (replaces
    create_weights).
  * ``forward``        — pure JAX computation for one (sharded) step; the
    global train step is differentiated with `jax.grad`, so no hand-written
    backward tasks (SURVEY.md section 7 step 2).
  * ``logical axes``   — names each tensor dimension so a strategy can map
    it to a mesh axis (replaces ParallelConfig dims + the mapper's
    slice_task routing, mapper.cc:346-440).
  * ``flops`` / ``bytes`` hooks — feed the analytic cost model used by the
    MCMC strategy search (replaces measure_operator_cost).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .tensor import Tensor

if TYPE_CHECKING:
    from .model import FFModel

# Logical axis vocabulary. "sample" is the batch dim; splitting it = DP
# (reference: sample-parallel). "channel*" splits = TP (reference:
# parameter/attribute parallel, linear.cu:144-270). "seq" split = SP/CP
# (new, absent in reference). "expert" split = EP (new).
SAMPLE = "sample"
CHANNEL = "channel"
CHANNEL_IN = "channel_in"
CHANNEL_OUT = "channel_out"
SEQ = "seq"
HEAD = "head"
HEIGHT = "height"
WIDTH = "width"
EXPERT = "expert"
VOCAB = "vocab"
LAYER = "layer"
TABLE = "table"  # stacked embedding tables (DLRM per-table placement)
REPLICA = None  # dimension never split


@dataclasses.dataclass
class WeightSpec:
    """Declaration of one trainable parameter of an op.

    ``fan_in``/``fan_out`` override shape-derived fans for fan-scaled
    initializers — needed for stacked weights (MoE experts (E, D, H),
    attention (E, H, Dh)) where the generic shape heuristic is wrong.
    """

    shape: Tuple[int, ...]
    dtype: jnp.dtype = jnp.float32
    initializer: str = "glorot"  # name into core.initializers registry
    axes: Tuple[Optional[str], ...] = None  # logical axis per dim
    custom_init: Optional[Callable] = None  # overrides `initializer`
    fan_in: Optional[int] = None
    fan_out: Optional[int] = None

    def __post_init__(self):
        if self.axes is None:
            self.axes = tuple([None] * len(self.shape))


@dataclasses.dataclass
class StateSpec:
    """Non-trainable per-op state (e.g. batch-norm running stats).

    The reference keeps these in dedicated Realm instances
    (include/model.h:883-899); here they live in a `state` pytree threaded
    functionally through the step.
    """

    shape: Tuple[int, ...]
    dtype: jnp.dtype = jnp.float32
    init_value: float = 0.0


class OpContext:
    """Per-invocation context handed to ``Op.forward``.

    ``mesh``/``op_strategy`` let collective-aware ops (ring attention for
    SP, fused MoE for EP) pick explicit shard_map implementations when
    their strategy maps an axis to a >1-sized mesh axis.
    """

    __slots__ = ("training", "rng", "seq_length", "state_in", "state_out",
                 "mesh", "op_strategy", "aux_loss", "nhwc_in", "nhwc_out")

    def __init__(self, training: bool, rng=None, seq_length: int = -1,
                 state_in: Optional[dict] = None, mesh=None,
                 op_strategy=None, nhwc_in: bool = False,
                 nhwc_out: bool = False):
        self.training = training
        self.rng = rng
        self.seq_length = seq_length
        self.state_in = state_in or {}
        self.state_out: dict = {}
        self.mesh = mesh
        self.op_strategy = op_strategy
        # ops may set a scalar auxiliary loss (e.g. MoE load-balancing);
        # the executor adds it to the training objective.
        self.aux_loss = None
        # NHWC layout residency (executor._compute_nhwc_resident): under
        # conv_layout="NHWC", values flow channels-last BETWEEN
        # conv-family ops; nhwc_in says this op's tensor inputs already
        # arrive NHWC-permuted, nhwc_out says its outputs should stay
        # NHWC (a consumer will read them that way). Both False outside
        # the executor walk — ops then do their own boundary transposes.
        self.nhwc_in = nhwc_in
        self.nhwc_out = nhwc_out

    def mesh_axis_size(self, logical_axis: str) -> int:
        """Size of the mesh axis a logical axis maps to (1 if unmapped)."""
        if self.mesh is None or self.op_strategy is None:
            return 1
        ax = self.op_strategy.mesh_axis_for(logical_axis)
        if ax is None or not isinstance(ax, str):
            return 1
        return self.mesh.shape.get(ax, 1)

    def mesh_axis_name(self, logical_axis: str):
        if self.op_strategy is None:
            return None
        ax = self.op_strategy.mesh_axis_for(logical_axis)
        return ax if isinstance(ax, str) else None


class Op:
    """Base class for all layers. Subclasses are pure-functional: they own
    no arrays, only shapes/attrs; arrays live in the executor's pytrees."""

    op_type: str = "op"

    def __init__(self, model: "FFModel", name: str, inputs: Sequence[Tensor]):
        self.model = model
        self.name = name
        self.inputs: List[Tensor] = list(inputs)
        self.outputs: List[Tensor] = []
        self.attrs: Dict = {}
        # finalize() is called by FFModel.add_op after subclass __init__.

    # ---- static graph contract ----
    def output_shapes(self) -> List[Tuple[int, ...]]:
        raise NotImplementedError

    def output_dtypes(self) -> List[jnp.dtype]:
        src = self.inputs[0].dtype if self.inputs else jnp.float32
        return [src for _ in self.output_shapes()]

    def weight_specs(self) -> Dict[str, WeightSpec]:
        return {}

    def state_specs(self) -> Dict[str, StateSpec]:
        return {}

    # Does the TRAINING-mode output depend on ctx.state_in? BatchNorm
    # reads state_in only to produce state_out (running-stat momentum)
    # — its training output uses batch statistics — so gradients are
    # state-independent and 1F1B's backward recompute may read the
    # already-advanced state row as a constant
    # (parallel/graph_pipeline.pipeline_1f1b_grads). A stateful op
    # whose training output DOES read state_in (e.g. a streaming/EMA
    # norm) must override this to True; StagedExecutor then rejects it
    # under the 1f1b schedule instead of silently mis-differentiating.
    training_output_reads_state: bool = False

    # ---- execution contract ----
    def forward(self, params: Dict[str, jax.Array], xs: List[jax.Array],
                ctx: OpContext) -> List[jax.Array]:
        raise NotImplementedError

    # ---- sharding contract ----
    def output_axes(self) -> List[Tuple[Optional[str], ...]]:
        """Logical axis name per output dim; default: sample on dim 0."""
        out = []
        for shp in [t.shape for t in self.outputs]:
            axes = [None] * len(shp)
            if len(shp) > 0:
                axes[0] = SAMPLE
            out.append(tuple(axes))
        return out

    def input_axes(self) -> List[Tuple[Optional[str], ...]]:
        """Logical axis name per input dim (used for resharding cost)."""
        out = []
        for t in self.inputs:
            axes = [None] * len(t.shape)
            if len(t.shape) > 0:
                axes[0] = SAMPLE
            out.append(tuple(axes))
        return out

    # ---- cost-model contract (replaces measure_operator_cost) ----
    def flops(self) -> float:
        """Forward FLOPs for the full (unsharded) op."""
        return 0.0

    def bytes_accessed(self) -> float:
        total = 0
        for t in list(self.inputs) + list(self.outputs):
            total += t.size_bytes()
        for spec in self.weight_specs().values():
            n = 1
            for s in spec.shape:
                n *= s
            total += n * jnp.dtype(spec.dtype).itemsize
        return float(total)

    def weight_bytes(self) -> float:
        total = 0
        for spec in self.weight_specs().values():
            n = 1
            for s in spec.shape:
                n *= s
            total += n * jnp.dtype(spec.dtype).itemsize
        return float(total)

    # ---- plumbing ----
    def finalize(self) -> None:
        """Create output Tensor handles from ``output_shapes``."""
        shapes = self.output_shapes()
        dtypes = self.output_dtypes()
        self.outputs = [
            Tensor(s, d, owner_op=self, owner_idx=i, name=f"{self.name}:out{i}")
            for i, (s, d) in enumerate(zip(shapes, dtypes))
        ]

    @property
    def output(self) -> Tensor:
        return self.outputs[0]

    def __repr__(self):
        ins = ", ".join(str(t.shape) for t in self.inputs)
        outs = ", ".join(str(t.shape) for t in self.outputs)
        return f"{type(self).__name__}({self.name}: [{ins}] -> [{outs}])"


# Registry: op_type string -> class, used by strategy file I/O, the ONNX
# importer and the torch.fx importer to construct ops by name.
OP_REGISTRY: Dict[str, type] = {}


def register_op(cls):
    OP_REGISTRY[cls.op_type] = cls
    return cls
