"""Keras callbacks (reference: python/flexflow/keras/callbacks.py and the
accuracy-assert callback used by tests/accuracy_tests.sh)."""

from __future__ import annotations


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", min_delta=0.0, patience=0,
                 mode="min"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = None

    def on_train_begin(self, logs=None):
        self.best = None
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        better = (self.best is None
                  or (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True


class VerifyMetrics(Callback):
    """Assert a final metric threshold (the accuracy_tests.sh pattern:
    examples/python/keras/accuracy.py)."""

    def __init__(self, metric="accuracy", threshold=0.9):
        self.metric = metric
        self.threshold = threshold
        self.last = None

    def on_epoch_end(self, epoch, logs=None):
        self.last = (logs or {}).get(self.metric)

    def on_train_end(self, logs=None):
        assert self.last is not None and self.last >= self.threshold, (
            f"{self.metric}={self.last} below threshold {self.threshold}")


class LearningRateScheduler(Callback):
    """Per-epoch LR schedule (reference:
    python/flexflow/keras/callbacks.py:49-62, which rewrote the
    config's learning rate each epoch). Here `schedule(epoch) -> lr`
    rescales the compiled step's traced lr input — the step never
    recompiles."""

    def __init__(self, schedule):
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        self.model.ffmodel.set_learning_rate(self.schedule(epoch))


class EpochVerifyMetrics(Callback):
    """Assert a metric threshold at EVERY epoch end (reference:
    python/flexflow/keras/callbacks.py:75-87; the per-epoch form of
    VerifyMetrics)."""

    def __init__(self, metric="accuracy", threshold=0.9):
        self.metric = metric
        self.threshold = threshold

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.metric)
        assert cur is not None and cur >= self.threshold, (
            f"epoch {epoch}: {self.metric}={cur} below threshold "
            f"{self.threshold}")
