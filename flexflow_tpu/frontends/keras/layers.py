"""Keras layer classes.

Reference: python/flexflow/keras/layers/*.py (Conv2D, Pooling, Dense,
Embedding, Merge, BN, Dropout, Flatten, Activation, Input; 1794 LoC).
Each layer is declarative; `emit` translates it onto the FFModel builder.
Layout follows the reference frontend: channels_first (N, C, H, W).
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

_uid = itertools.count()


def reset_layer_uids() -> None:
    """Restart layer auto-naming (the keras backend.clear_session
    analog). Weight-init keys fold on op NAMES, so deterministic names
    make model construction reproducible regardless of what was built
    earlier in the process — tests reset between cases for exactly
    that."""
    global _uid
    _uid = itertools.count()
    Layer._counter = itertools.count()


class KTensor:
    """Symbolic Keras-level tensor: records the producing layer + inputs."""

    def __init__(self, shape, dtype=jnp.float32, layer=None, inputs=(),
                 ff_name: Optional[str] = None):
        self.shape = tuple(shape)  # without batch dim for Input specs
        self.dtype = dtype
        self.layer = layer
        self.inputs = list(inputs)
        self.ff_name = ff_name
        self.uid = next(_uid)


class Layer:
    _counter = itertools.count()

    def __init__(self, name: Optional[str] = None, input_shape=None):
        self.name = name or f"{type(self).__name__.lower()}_{next(Layer._counter)}"
        # keras convention: first layer of a Sequential may carry the
        # (batchless) input shape
        self._input_shape = tuple(input_shape) if input_shape else None

    def __call__(self, x):
        xs = x if isinstance(x, (list, tuple)) else [x]
        out_shape = self.output_shape([t.shape for t in xs])
        return KTensor(out_shape, layer=self, inputs=xs)

    def output_shape(self, in_shapes: List[Tuple[int, ...]]):
        return tuple(in_shapes[0])

    def emit(self, ff, ins):
        raise NotImplementedError


def Input(shape: Sequence[int], dtype=jnp.float32,
          name: Optional[str] = None) -> KTensor:
    return KTensor(tuple(shape), dtype=dtype,
                   ff_name=name or f"input_{next(_uid)}")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _pad_for(padding, kh, kw):
    if padding == "same":
        return kh // 2, kw // 2
    return 0, 0


class Conv2D(Layer):
    def __init__(self, filters, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, use_bias=True,
                 name=None, **kw):
        super().__init__(name, kw.get("input_shape"))
        self.filters = filters
        self.kernel = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias

    def output_shape(self, in_shapes):
        c, h, w = in_shapes[0]
        kh, kw = self.kernel
        sh, sw = self.strides
        ph, pw = _pad_for(self.padding, kh, kw)
        return (self.filters, (h + 2 * ph - kh) // sh + 1,
                (w + 2 * pw - kw) // sw + 1)

    def emit(self, ff, ins):
        kh, kw = self.kernel
        ph, pw = _pad_for(self.padding, kh, kw)
        return ff.conv2d(ins[0], self.filters, kh, kw, *self.strides,
                         ph, pw, activation=self.activation,
                         use_bias=self.use_bias, name=self.name)


class _Pool2D(Layer):
    pool_type = "max"

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name=None):
        super().__init__(name)
        self.pool = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool
        self.padding = padding

    def output_shape(self, in_shapes):
        c, h, w = in_shapes[0]
        kh, kw = self.pool
        sh, sw = self.strides
        ph, pw = _pad_for(self.padding, kh, kw)
        return (c, (h + 2 * ph - kh) // sh + 1,
                (w + 2 * pw - kw) // sw + 1)

    def emit(self, ff, ins):
        kh, kw = self.pool
        ph, pw = _pad_for(self.padding, kh, kw)
        return ff.pool2d(ins[0], kh, kw, *self.strides, ph, pw,
                         pool_type=self.pool_type, name=self.name)


class MaxPooling2D(_Pool2D):
    pool_type = "max"


class AveragePooling2D(_Pool2D):
    pool_type = "avg"


class Dense(Layer):
    def __init__(self, units, activation=None, use_bias=True, name=None,
                 **kw):
        super().__init__(name, kw.get("input_shape"))
        self.units = units
        self.activation = activation
        self.use_bias = use_bias

    def output_shape(self, in_shapes):
        return tuple(in_shapes[0][:-1]) + (self.units,)

    def emit(self, ff, ins):
        act = self.activation if self.activation != "softmax" else None
        t = ff.dense(ins[0], self.units, activation=act,
                     use_bias=self.use_bias, name=self.name)
        if self.activation == "softmax":
            t = ff.softmax(t, name=f"{self.name}_softmax")
        return t


class Embedding(Layer):
    def __init__(self, input_dim, output_dim, name=None, **kw):
        super().__init__(name, kw.get("input_shape"))
        self.input_dim = input_dim
        self.output_dim = output_dim

    def output_shape(self, in_shapes):
        return tuple(in_shapes[0]) + (self.output_dim,)

    def emit(self, ff, ins):
        return ff.embedding(ins[0], self.input_dim, self.output_dim,
                            aggr="none", name=self.name)


class Flatten(Layer):
    def output_shape(self, in_shapes):
        n = 1
        for s in in_shapes[0]:
            n *= s
        return (n,)

    def emit(self, ff, ins):
        return ff.flat(ins[0], name=self.name)


class GlobalAveragePooling1D(Layer):
    """(steps, features) -> (features,): mean over the steps axis — the
    standard head after Embedding; lowers to the generic reduce op."""

    def output_shape(self, in_shapes):
        if len(in_shapes[0]) != 2:
            raise ValueError(
                f"GlobalAveragePooling1D expects (steps, features) "
                f"inputs, got {in_shapes[0]}")
        return (in_shapes[0][-1],)

    def emit(self, ff, ins):
        return ff.reduce_mean(ins[0], axis=1, name=self.name)


class LayerNormalization(Layer):
    """Normalizes over the last axis (keras default axis=-1) ->
    FFModel.layer_norm. Fail-loudly policy (like the module's _same_pad/
    _act): unsupported keras configurations raise instead of silently
    normalizing the wrong thing."""

    def __init__(self, axis=-1, epsilon=1e-3, center=True, scale=True,
                 name=None, **kw):
        super().__init__(name, kw.get("input_shape"))
        self.axis = axis
        self.epsilon = epsilon
        if center != scale:
            raise NotImplementedError(
                "LayerNormalization with center != scale would train a "
                "parameter keras would not create; use both or neither")
        self.affine = bool(center and scale)

    def emit(self, ff, ins):
        rank = len(ins[0].shape)
        if self.axis not in (-1, rank - 1):
            raise NotImplementedError(
                f"LayerNormalization axis={self.axis}: only last-dim "
                f"normalization is supported")
        return ff.layer_norm(ins[0], eps=self.epsilon,
                             elementwise_affine=self.affine,
                             name=self.name)


class Reshape(Layer):
    """Batch-preserving reshape (reference keras frontend Reshape →
    FFModel::reshape; target_shape excludes the batch dim)."""

    def __init__(self, target_shape, name=None, **kw):
        super().__init__(name, kw.get("input_shape"))
        self.target_shape = tuple(int(s) for s in target_shape)

    def output_shape(self, in_shapes):
        return self.target_shape

    def emit(self, ff, ins):
        bs = ins[0].shape[0]
        return ff.reshape(ins[0], (bs,) + self.target_shape,
                          name=self.name)


class Dropout(Layer):
    def __init__(self, rate, name=None, **kw):
        super().__init__(name, kw.get("input_shape"))
        self.rate = rate

    def emit(self, ff, ins):
        return ff.dropout(ins[0], self.rate, name=self.name)


class BatchNormalization(Layer):
    def emit(self, ff, ins):
        return ff.batch_norm(ins[0], relu=False, name=self.name)


class Activation(Layer):
    def __init__(self, activation, name=None):
        super().__init__(name)
        self.activation = activation

    def emit(self, ff, ins):
        if self.activation == "softmax":
            return ff.softmax(ins[0], name=self.name)
        return getattr(ff, self.activation)(ins[0], name=self.name)


class Concatenate(Layer):
    def __init__(self, axis=1, name=None):
        super().__init__(name)
        self.axis = axis

    def output_shape(self, in_shapes):
        out = list(in_shapes[0])
        ax = self.axis - 1 if self.axis > 0 else self.axis  # batchless
        out[ax] = sum(s[ax] for s in in_shapes)
        return tuple(out)

    def emit(self, ff, ins):
        return ff.concat(ins, axis=self.axis, name=self.name)


class _Merge(Layer):
    mode = "add"

    def emit(self, ff, ins):
        return getattr(ff, self.mode)(ins[0], ins[1], name=self.name)


class Add(_Merge):
    mode = "add"


class Subtract(_Merge):
    mode = "subtract"


class Multiply(_Merge):
    mode = "multiply"


class LSTM(Layer):
    def __init__(self, units, return_sequences=False, name=None, **kw):
        super().__init__(name, kw.get("input_shape"))
        self.units = units
        self.return_sequences = return_sequences

    def output_shape(self, in_shapes):
        t, d = in_shapes[0]
        if self.return_sequences:
            return (t, self.units)
        return (self.units,)

    def emit(self, ff, ins):
        return ff.lstm(ins[0], self.units,
                       return_sequences=self.return_sequences,
                       name=self.name)
