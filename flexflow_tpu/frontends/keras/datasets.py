"""Keras-style dataset loaders.

Reference: python/flexflow/keras/datasets/{mnist,cifar10,reuters}.py —
each downloads a public archive and returns (x_train, y_train),
(x_test, y_test) numpy tuples.

This environment is zero-egress, so loading order is:
  1. a locally cached archive in ``~/.keras/datasets`` (same cache path
     the reference's loaders populate) or ``$FLEXFLOW_TPU_DATA``;
  2. otherwise, deterministic synthetic data with the exact shapes,
     dtypes, and label ranges of the real datasets (the reference's own
     fallback philosophy: synthetic input when no --dataset is given,
     alexnet.cc:100-104), with a one-line warning.

Model code is therefore portable: the same script runs here and against
real data when a cache is present.
"""

from __future__ import annotations

import gzip
import os
import pickle
import sys
import tarfile
from typing import Tuple

import numpy as np

Arrays = Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]


def _cache_dirs():
    dirs = []
    env = os.environ.get("FLEXFLOW_TPU_DATA")
    if env:
        dirs.append(env)
    dirs.append(os.path.expanduser("~/.keras/datasets"))
    return dirs


def _find(fname: str):
    for d in _cache_dirs():
        p = os.path.join(d, fname)
        if os.path.exists(p):
            return p
    return None


def _warn_synthetic(name: str):
    print(f"[flexflow_tpu.keras.datasets] no local cache for {name}; "
          "returning deterministic synthetic data with real shapes "
          "(set FLEXFLOW_TPU_DATA or populate ~/.keras/datasets)",
          file=sys.stderr)


def _synthetic_images(shape, num_classes, n_train, n_test, seed) -> Arrays:
    rng = np.random.RandomState(seed)
    xtr = rng.randint(0, 256, (n_train,) + shape).astype(np.uint8)
    xte = rng.randint(0, 256, (n_test,) + shape).astype(np.uint8)
    ytr = rng.randint(0, num_classes, (n_train,)).astype(np.int64)
    yte = rng.randint(0, num_classes, (n_test,)).astype(np.int64)
    return (xtr, ytr), (xte, yte)


class mnist:
    """(60000, 28, 28) uint8 train / (10000, 28, 28) test, labels 0-9."""

    @staticmethod
    def load_data(path: str = "mnist.npz") -> Arrays:
        p = _find(os.path.basename(path))
        if p:
            with np.load(p, allow_pickle=True) as f:
                return ((f["x_train"], f["y_train"]),
                        (f["x_test"], f["y_test"]))
        _warn_synthetic("mnist")
        return _synthetic_images((28, 28), 10, 60000, 10000, seed=1)


class cifar10:
    """(50000, 32, 32, 3) uint8 train / (10000, ...) test, labels 0-9."""

    @staticmethod
    def load_data() -> Arrays:
        p = _find("cifar-10-batches-py") or _find("cifar-10-python.tar.gz")
        if p and os.path.isdir(p):
            return cifar10._from_batches(p)
        if p:  # tarball: extract once (next to it if writable, else /tmp)
            try:
                dst = os.path.dirname(p)
                if not os.access(dst, os.W_OK):
                    import tempfile
                    # fixed per-user path so the extract-once check works
                    # across calls/processes on a read-only cache
                    dst = os.path.join(tempfile.gettempdir(),
                                       f"flexflow_tpu_cifar10_{os.getuid()}")
                    os.makedirs(dst, exist_ok=True)
                extracted = os.path.join(dst, "cifar-10-batches-py")
                if not os.path.isdir(extracted):
                    # extract to a unique dir, then atomically rename so
                    # concurrent processes never see a partial extraction
                    import tempfile
                    work = tempfile.mkdtemp(dir=dst)
                    with tarfile.open(p) as tar:
                        tar.extractall(work)  # noqa: S202 - trusted cache
                    try:
                        os.rename(os.path.join(work,
                                               "cifar-10-batches-py"),
                                  extracted)
                    except OSError:
                        pass  # another process won the race
                return cifar10._from_batches(extracted)
            except Exception as e:
                print(f"[flexflow_tpu.keras.datasets] cifar10 cache "
                      f"unusable ({e}); using synthetic", file=sys.stderr)
        _warn_synthetic("cifar10")
        (xtr, ytr), (xte, yte) = _synthetic_images(
            (32, 32, 3), 10, 50000, 10000, seed=2)
        return (xtr, ytr.reshape(-1, 1)), (xte, yte.reshape(-1, 1))

    @staticmethod
    def _from_batches(d: str) -> Arrays:
        def load_batch(fp):
            with open(fp, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            x = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            y = np.asarray(batch[b"labels"], np.int64)
            return x, y

        xs, ys = zip(*[load_batch(os.path.join(d, f"data_batch_{i}"))
                       for i in range(1, 6)])
        xte, yte = load_batch(os.path.join(d, "test_batch"))
        return ((np.concatenate(xs), np.concatenate(ys).reshape(-1, 1)),
                (xte, yte.reshape(-1, 1)))


class reuters:
    """Variable-length int sequences, 46 topics (reference reuters.py)."""

    @staticmethod
    def load_data(num_words: int = None, maxlen: int = None,
                  test_split: float = 0.2, seed: int = 113,
                  skip_top: int = 0, oov_char: int = 2) -> Arrays:
        p = _find("reuters.npz")
        if p:
            with np.load(p, allow_pickle=True) as f:
                xs, labels = f["x"], f["y"]
            rng = np.random.RandomState(seed)
            order = rng.permutation(len(xs))
            xs, labels = xs[order], labels[order]
            if maxlen:  # Keras semantics: drop sequences longer than maxlen
                keep = [i for i, x in enumerate(xs) if len(x) <= maxlen]
                xs, labels = xs[keep], labels[keep]
            if num_words or skip_top:
                # Keras/reference semantics (reference reuters.py:79-80):
                # words outside [skip_top, num_words) become oov_char so
                # sequence lengths are preserved (oov_char=None drops them)
                hi = num_words or np.inf
                if oov_char is None:
                    xs = np.array([[w for w in x if skip_top <= w < hi]
                                   for x in xs], dtype=object)
                else:
                    xs = np.array([[w if skip_top <= w < hi else oov_char
                                    for w in x]
                                   for x in xs], dtype=object)
            split = int(len(xs) * (1 - test_split))
            return ((xs[:split], labels[:split]),
                    (xs[split:], labels[split:]))
        _warn_synthetic("reuters")
        rng = np.random.RandomState(seed)
        vocab = num_words or 10000
        n_train, n_test = 8982, 2246
        hi = max(6, maxlen or 200)  # sequence lengths in [5, hi)

        def seqs(n):
            return np.array(
                [rng.randint(1, vocab, rng.randint(5, hi)).tolist()
                 for _ in range(n)], dtype=object)

        return ((seqs(n_train), rng.randint(0, 46, n_train)),
                (seqs(n_test), rng.randint(0, 46, n_test)))


def pad_sequences(seqs, maxlen: int, dtype=np.int32, value: int = 0,
                  truncating: str = "pre", padding: str = "pre"
                  ) -> np.ndarray:
    """Pad/truncate to (n, maxlen) with Keras defaults: 'pre' truncation
    keeps the LAST maxlen tokens, 'pre' padding left-pads."""
    out = np.full((len(seqs), maxlen), value, dtype)
    for i, s in enumerate(seqs):
        s = list(s)
        s = s[-maxlen:] if truncating == "pre" else s[:maxlen]
        if padding == "pre":
            out[i, maxlen - len(s):] = s
        else:
            out[i, :len(s)] = s
    return out
