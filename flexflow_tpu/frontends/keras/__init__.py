"""Keras-compatible frontend.

Reference: python/flexflow/keras/ — Sequential/Model over a shared base
(keras/models/base_model.py), layer classes translating 1:1 onto FFModel
builder calls, optimizer/loss/metric name shims, callbacks. Same usage:

    from flexflow_tpu.frontends import keras
    model = keras.Sequential([
        keras.layers.Conv2D(32, (3, 3), activation="relu",
                            input_shape=(3, 32, 32)),
        keras.layers.Flatten(),
        keras.layers.Dense(10, activation="softmax"),
    ])
    model.compile(optimizer="sgd",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=5)
"""

from . import datasets, layers
from .callbacks import (Callback, EarlyStopping, EpochVerifyMetrics,
                        LearningRateScheduler, VerifyMetrics)
from .models import Model, Sequential
from .optimizers import SGD, Adam

__all__ = ["datasets", "layers", "Model", "Sequential", "SGD", "Adam",
           "Callback", "EarlyStopping", "EpochVerifyMetrics",
           "LearningRateScheduler", "VerifyMetrics"]
