"""Keras Model/Sequential.

Reference: python/flexflow/keras/models/base_model.py — compile() builds
the FFModel graph + optimizer (:127-193), fit() wires dataloaders and
runs the per-iteration train loop (:347-424). Here compile() emits the
recorded layer DAG onto an FFModel and fit() delegates to FFModel.fit
with callback hooks.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ...config import FFConfig
from ...model import FFModel
from .layers import Input, KTensor, Layer
from .optimizers import resolve as resolve_optimizer

_LOSS_ALIASES = {
    "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
    "categorical_crossentropy": "categorical_crossentropy",
    "mean_squared_error": "mean_squared_error",
    "mse": "mean_squared_error",
    "binary_crossentropy": "binary_crossentropy",
}


class Model:
    def __init__(self, inputs=None, outputs=None, name: str = "model",
                 config: Optional[FFConfig] = None, mesh=None,
                 strategy=None):
        self.name = name
        self.inputs: List[KTensor] = (
            inputs if isinstance(inputs, (list, tuple))
            else [inputs] if inputs is not None else [])
        self.outputs: List[KTensor] = (
            outputs if isinstance(outputs, (list, tuple))
            else [outputs] if outputs is not None else [])
        self.config = config
        self.mesh = mesh
        self.strategy = strategy
        self.ffmodel: Optional[FFModel] = None
        self.stop_training = False

    # ---- graph emission ----
    def _walk(self, mapping: Dict[int, object], node_fn):
        """Memoized DFS over the recorded KTensor DAG from inputs (seeded
        in `mapping`) to outputs, applying node_fn(kt, mapped_inputs) at
        each layer invocation — shared by FFModel emission and nested
        replay."""
        def visit(kt: KTensor):
            if kt.uid in mapping:
                return mapping[kt.uid]
            ins = [visit(i) for i in kt.inputs]
            out = node_fn(kt, ins)
            mapping[kt.uid] = out
            return out

        return [visit(o) for o in self.outputs]

    def _emit(self, batch_size: int) -> FFModel:
        cfg = self.config or FFConfig()
        cfg.batch_size = batch_size
        ff = FFModel(cfg, mesh=self.mesh, strategy=self.strategy)
        mapping: Dict[int, object] = {}
        for kt in self.inputs:
            mapping[kt.uid] = ff.create_tensor(
                (batch_size,) + kt.shape, dtype=kt.dtype, name=kt.ff_name)
        self._walk(mapping, lambda kt, ins: kt.layer.emit(ff, ins))
        return ff

    # ---- nested models (reference: models used as layers in the
    # func_*_nested / seq_*_nested examples) ----
    def __call__(self, inputs):
        """Use this model as a layer inside another model: replays the
        recorded layer graph onto the caller's symbolic tensors, making
        the nested layers part of the outer graph.

        Single-use: calling the same Model twice would need weight
        sharing between the two copies (keras semantics), which this
        frontend does not implement — it raises instead of silently
        duplicating weights."""
        if getattr(self, "_nested_called", False):
            raise NotImplementedError(
                f"model {self.name!r} already used as a layer once; "
                f"reuse would require weight sharing between the copies")
        if self.ffmodel is not None:
            # trained/compiled weights live in this model's own FFModel;
            # the replay would re-emit FRESH weights into the outer
            # graph — fail loudly rather than silently dropping training
            # (same policy as the reuse case above)
            raise NotImplementedError(
                f"model {self.name!r} was already compiled/trained; "
                f"nesting would silently reinitialize its weights — "
                f"nest it before training, or transfer weights via "
                f"get_weights/set_weights after compiling the outer "
                f"model")
        if not self.inputs and hasattr(self, "_build_graph"):
            self._build_graph()  # Sequential builds lazily
        assert self.inputs and self.outputs, (
            "model has no recorded graph to nest")
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        assert len(ins) == len(self.inputs), (
            f"nested model {self.name!r} takes {len(self.inputs)} "
            f"inputs, got {len(ins)}")
        mapping = {kt.uid: new for kt, new in zip(self.inputs, ins)}
        outs = self._walk(
            mapping,
            lambda kt, new_ins: kt.layer(
                new_ins if len(new_ins) > 1 else new_ins[0]))
        self._nested_called = True  # only after a successful replay
        return outs if len(outs) > 1 else outs[0]

    # ---- keras API ----
    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics=None, batch_size: Optional[int] = None, **kw):
        self._optimizer = resolve_optimizer(optimizer)
        self._loss = _LOSS_ALIASES.get(loss, loss)
        self._metrics = list(metrics or [])
        self._batch_size = batch_size
        self._compiled = False

    def _ensure_ff(self, batch_size: int):
        if self.ffmodel is None or not self._compiled:
            self.ffmodel = self._emit(batch_size)
            self.ffmodel.compile(optimizer=self._optimizer,
                                 loss_type=self._loss,
                                 metrics=self._metrics)
            self._compiled = True

    def fit(self, x, y, batch_size: int = 64, epochs: int = 1,
            callbacks: Sequence = (), shuffle: bool = True,
            verbose: bool = True, steps_per_dispatch="auto"):
        xs = x if isinstance(x, (list, tuple)) else [x]
        bs = self._batch_size or batch_size
        self._ensure_ff(bs)  # builds Sequential graphs lazily
        assert len(xs) == len(self.inputs), (
            f"model has {len(self.inputs)} inputs, got {len(xs)} arrays")
        inputs = {}
        for kt, arr in zip(self.inputs, xs):
            name = self.ffmodel.input_tensors[
                self.inputs.index(kt)].name
            inputs[name] = np.asarray(arr)

        for cb in callbacks:
            cb.set_model(self)
        self.stop_training = False
        history = []
        for cb in callbacks:
            cb.on_train_begin()
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            h = self.ffmodel.fit(inputs, np.asarray(y), batch_size=bs,
                                 epochs=1, shuffle=shuffle,
                                 verbose=False,
                                 steps_per_dispatch=steps_per_dispatch)
            logs = h[-1]
            logs["epoch"] = epoch
            history.append(logs)
            if verbose:
                acc = (f" accuracy={logs['accuracy']:.4f}"
                       if "accuracy" in logs else "")
                print(f"epoch {epoch}: loss={logs['loss']:.4f}{acc} "
                      f"({logs['throughput']:.1f} samples/s)")
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        for cb in callbacks:
            cb.on_train_end(history[-1] if history else None)
        return history

    def evaluate(self, x, y, batch_size: int = 64):
        xs = x if isinstance(x, (list, tuple)) else [x]
        bs = self._batch_size or batch_size
        self._ensure_ff(bs)
        inputs = {}
        for i, arr in enumerate(xs):
            inputs[self.ffmodel.input_tensors[i].name] = np.asarray(arr)
        return self.ffmodel.evaluate(inputs, np.asarray(y), batch_size=bs)

    def predict(self, x, batch_size: int = 64):
        xs = x if isinstance(x, (list, tuple)) else [x]
        bs = self._batch_size or batch_size
        self._ensure_ff(bs)
        outs = []
        n = len(xs[0])
        n_batches = (n + bs - 1) // bs
        for s in range(n_batches):
            batch = {}
            valid = min(bs, n - s * bs)
            for i, arr in enumerate(xs):
                part = np.asarray(arr[s * bs:s * bs + valid])
                if valid < bs:  # pad the tail to keep shapes static
                    pad = np.repeat(part[:1], bs - valid, axis=0)
                    part = np.concatenate([part, pad], axis=0)
                batch[self.ffmodel.input_tensors[i].name] = part
            out = np.asarray(self.ffmodel.forward(batch))
            outs.append(out[:valid])
        return np.concatenate(outs, axis=0)

    def build_model(self, batch_size: int = 64) -> FFModel:
        """Force FFModel construction (after compile()) without training
        a step — for host weight access before the first fit(), e.g.
        net2net weight surgery (examples/python/keras/*_net2net.py).
        Returns the built FFModel."""
        self._ensure_ff(self._batch_size or batch_size)
        return self.ffmodel

    def summary(self):
        self._ensure_ff(self._batch_size or 64)
        print(self.ffmodel.summary())


class Sequential(Model):
    def __init__(self, layers: Sequence = (), name: str = "sequential",
                 config: Optional[FFConfig] = None, mesh=None,
                 strategy=None):
        super().__init__(name=name, config=config, mesh=mesh,
                         strategy=strategy)
        self._layers: List[Layer] = []
        self._input_shape = None
        for l in layers:
            self.add(l)

    def add(self, layer: Layer):
        self._layers.append(layer)
        return self

    def _build_graph(self):
        assert self._layers, "empty Sequential"
        first = self._layers[0]
        in_shape = getattr(first, "_input_shape", None) or self._input_shape
        assert in_shape is not None, (
            "first layer needs input_shape= or call build(input_shape)")
        import jax.numpy as jnp
        dtype = jnp.int32 if type(first).__name__ == "Embedding" else jnp.float32
        t = Input(in_shape, dtype=dtype)
        self.inputs = [t]
        for l in self._layers:
            t = l(t)
        self.outputs = [t]

    def build(self, input_shape):
        self._input_shape = tuple(input_shape)
        return self

    def _ensure_ff(self, batch_size: int):
        if not self.inputs:
            self._build_graph()
        super()._ensure_ff(batch_size)
