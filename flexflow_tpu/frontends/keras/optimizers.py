"""Keras optimizer shims (reference: keras optimizer translation in
base_model.compile, base_model.py:127-193)."""

from __future__ import annotations

from ...core.optimizers import AdamOptimizer, Optimizer, SGDOptimizer


def SGD(learning_rate=0.01, momentum=0.0, nesterov=False, **kw):
    return SGDOptimizer(lr=learning_rate, momentum=momentum,
                        nesterov=nesterov)


def Adam(learning_rate=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-7,
         **kw):
    return AdamOptimizer(lr=learning_rate, beta1=beta_1, beta2=beta_2,
                         epsilon=epsilon)


def resolve(opt) -> Optimizer:
    if isinstance(opt, Optimizer):
        return opt
    if isinstance(opt, str):
        name = opt.lower()
        if name == "sgd":
            return SGD()
        if name == "adam":
            return Adam()
        raise ValueError(f"unknown optimizer {opt!r}")
    raise TypeError(f"cannot resolve optimizer from {type(opt)}")
