"""PyTorch frontend via torch.fx.

Reference: python/flexflow/torch/fx.py (symbolic_trace graph walk -> `.ff`
text format) + torch/model.py (`PyTorchModel` replays the file onto an
FFModel). Here both halves live together:

  * torch_to_ff(module) -> list of op descriptor lines (the reference's
    .ff text format, writable with export_ff)
  * PyTorchModel(module_or_path).apply(ffmodel, input_tensors) -> output
    tensors, optionally importing the torch weights.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import torch
import torch.fx
import torch.nn as nn

from ..tensor import Tensor


def _node_name(node) -> str:
    return node.name.replace(".", "_")


class _OpDesc:
    def __init__(self, name: str, op_type: str, inputs: List[str], **attrs):
        self.name = name
        self.op_type = op_type
        self.inputs = inputs
        self.attrs = attrs

    def to_line(self) -> str:
        # reference .ff line shape: name, input names, op type, attrs
        ins = ":".join(self.inputs)
        attrs = ";".join(f"{k}={v}" for k, v in sorted(self.attrs.items()))
        return f"{self.name}, {ins}, {self.op_type}, {attrs}"


def trace_module(module: nn.Module) -> List[_OpDesc]:
    """symbolic_trace + graph walk (reference fx.py:47-478)."""
    traced = torch.fx.symbolic_trace(module)
    descs: List[_OpDesc] = []
    modules = dict(traced.named_modules())
    for node in traced.graph.nodes:
        name = _node_name(node)
        ins = [_node_name(a) for a in node.args
               if isinstance(a, torch.fx.Node)]
        if node.op == "placeholder":
            descs.append(_OpDesc(name, "input", []))
        elif node.op == "output":
            descs.append(_OpDesc(name, "output", ins))
        elif node.op == "call_module":
            m = modules[node.target]
            descs.append(_module_desc(name, m, ins, node.target))
        elif node.op == "call_function":
            descs.append(_function_desc(name, node, ins))
        elif node.op == "call_method":
            descs.append(_method_desc(name, node, ins))
    return descs


def _module_desc(name, m, ins, target) -> _OpDesc:
    if isinstance(m, nn.Conv2d):
        return _OpDesc(name, "conv2d", ins, target=target,
                       out=m.out_channels, kh=m.kernel_size[0],
                       kw=m.kernel_size[1], sh=m.stride[0], sw=m.stride[1],
                       ph=m.padding[0], pw=m.padding[1], groups=m.groups,
                       bias=int(m.bias is not None))
    if isinstance(m, nn.Linear):
        return _OpDesc(name, "linear", ins, target=target,
                       out=m.out_features, bias=int(m.bias is not None))
    if isinstance(m, nn.BatchNorm2d):
        return _OpDesc(name, "batch_norm", ins, target=target)
    if isinstance(m, nn.MaxPool2d):
        k = m.kernel_size if isinstance(m.kernel_size, int) else m.kernel_size[0]
        s = m.stride if isinstance(m.stride, int) else m.stride[0]
        p = m.padding if isinstance(m.padding, int) else m.padding[0]
        return _OpDesc(name, "pool2d", ins, target=target, kind="max",
                       k=k, s=s or k, p=p)
    if isinstance(m, nn.AvgPool2d):
        k = m.kernel_size if isinstance(m.kernel_size, int) else m.kernel_size[0]
        s = m.stride if isinstance(m.stride, int) else m.stride[0]
        p = m.padding if isinstance(m.padding, int) else m.padding[0]
        return _OpDesc(name, "pool2d", ins, target=target, kind="avg",
                       k=k, s=s or k, p=p)
    if isinstance(m, nn.ReLU):
        return _OpDesc(name, "relu", ins, target=target)
    if isinstance(m, nn.Sigmoid):
        return _OpDesc(name, "sigmoid", ins, target=target)
    if isinstance(m, nn.Tanh):
        return _OpDesc(name, "tanh", ins, target=target)
    if isinstance(m, nn.GELU):
        return _OpDesc(name, "gelu", ins, target=target)
    if isinstance(m, nn.Softmax):
        return _OpDesc(name, "softmax", ins, target=target)
    if isinstance(m, nn.Dropout):
        return _OpDesc(name, "dropout", ins, target=target, rate=m.p)
    if isinstance(m, nn.Flatten):
        return _OpDesc(name, "flat", ins, target=target)
    if isinstance(m, nn.Embedding):
        return _OpDesc(name, "embedding", ins, target=target,
                       vocab=m.num_embeddings, dim=m.embedding_dim)
    if isinstance(m, nn.LayerNorm):
        if len(m.normalized_shape) != 1:
            raise NotImplementedError(
                f"nn.LayerNorm over {m.normalized_shape}: only last-dim "
                f"LayerNorm is supported")
        return _OpDesc(name, "layer_norm", ins, target=target,
                       eps=m.eps,
                       affine=int(m.elementwise_affine))
    raise NotImplementedError(f"unsupported torch module {type(m)}")


def _function_desc(name, node, ins) -> _OpDesc:
    import operator
    fn = node.target
    table = {
        operator.add: "add", torch.add: "add",
        operator.sub: "subtract", torch.sub: "subtract",
        operator.mul: "multiply", torch.mul: "multiply",
        operator.truediv: "divide",
        torch.relu: "relu", nn.functional.relu: "relu",
        torch.sigmoid: "sigmoid", torch.tanh: "tanh",
        nn.functional.gelu: "gelu",
        nn.functional.softmax: "softmax",
        torch.flatten: "flat",
        torch.cat: "concat",
    }
    if fn in table:
        op = table[fn]
        attrs = {}
        if op == "concat":
            attrs["axis"] = node.kwargs.get("dim", 1)
            # cat takes a list as first arg
            ins = [_node_name(a) for a in node.args[0]]
        return _OpDesc(name, op, ins, **attrs)
    if fn is torch.mean:
        return _reduce_mean_desc(name, node, ins)
    raise NotImplementedError(f"unsupported torch function {fn}")


def _reduce_mean_desc(name, node, ins) -> _OpDesc:
    """x.mean(dim)/torch.mean(x, dim) with a single int dim -> the
    generic reduce op. Everything the op cannot lower (full-tensor or
    multi-dim means, the sample dim, a kwarg-passed input tensor)
    raises HERE — trace time — per the frontend's contract."""
    if not ins:
        raise NotImplementedError(
            f"mean at {name}: pass the tensor positionally "
            f"(torch.mean(input=x, ...) hides it from the fx arg list)")
    dim = node.kwargs.get("dim")
    if dim is None and len(node.args) > 1:
        dim = node.args[1]
    if not isinstance(dim, int):
        raise NotImplementedError(
            f"mean at {name}: exactly one int dim is supported, "
            f"got {dim!r}")
    if dim < 0:
        # normalize against the traced rank when fx shape metadata is
        # available, so .mean(-rank) is rejected here, not deep in Reduce
        tm = getattr(node.args[0], "meta", {}).get("tensor_meta")
        if tm is not None:
            dim += len(tm.shape)
    if dim == 0:
        raise NotImplementedError(
            f"mean at {name}: dim 0 is the sample dim and cannot be "
            f"reduced")
    keepdim = bool(node.kwargs.get("keepdim", False)
                   or (len(node.args) > 2 and node.args[2]))
    return _OpDesc(name, "reduce_mean", ins[:1], axis=dim,
                   keepdims=int(keepdim))


def _method_desc(name, node, ins) -> _OpDesc:
    if node.target in ("view", "reshape"):
        dims = [d for d in node.args[1:]]
        return _OpDesc(name, "reshape", ins[:1],
                       shape=",".join(str(d) for d in dims))
    if node.target == "flatten":
        return _OpDesc(name, "flat", ins[:1])
    if node.target == "transpose":
        return _OpDesc(name, "transpose", ins[:1], d0=node.args[1],
                       d1=node.args[2])
    if node.target == "mean":
        return _reduce_mean_desc(name, node, ins)
    raise NotImplementedError(f"unsupported torch method {node.target}")


def export_ff(module: nn.Module, path: str) -> None:
    """Write the reference-style .ff text file (fx.py output format)."""
    with open(path, "w") as f:
        for d in trace_module(module):
            f.write(d.to_line() + "\n")


class PyTorchModel:
    """Replay a traced torch module (or exported .ff file) onto an
    FFModel (reference torch/model.py)."""

    def __init__(self, module_or_path):
        if isinstance(module_or_path, nn.Module):
            self.module: Optional[nn.Module] = module_or_path
            self.descs = trace_module(module_or_path)
        else:
            self.module = None
            self.descs = self._parse(module_or_path)

    @staticmethod
    def _parse(path: str) -> List[_OpDesc]:
        descs = []
        for line in open(path):
            line = line.strip()
            if not line:
                continue
            name, ins, op_type, attrs_s = [p.strip()
                                           for p in line.split(",", 3)]
            ins_list = [i for i in ins.split(":") if i]
            attrs = {}
            for kv in attrs_s.split(";"):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    attrs[k] = v
            descs.append(_OpDesc(name, op_type, ins_list, **attrs))
        return descs

    def apply(self, ffmodel, input_tensors: Sequence[Tensor]):
        """Emit the graph; returns the output tensors."""
        values: Dict[str, Tensor] = {}
        it = iter(input_tensors)
        outputs = []
        for d in self.descs:
            a = {k: _maybe_num(v) for k, v in d.attrs.items()}
            if d.op_type == "input":
                values[d.name] = next(it)
            elif d.op_type == "output":
                outputs = [values[i] for i in d.inputs]
            elif d.op_type == "conv2d":
                values[d.name] = ffmodel.conv2d(
                    values[d.inputs[0]], int(a["out"]), int(a["kh"]),
                    int(a["kw"]), int(a["sh"]), int(a["sw"]), int(a["ph"]),
                    int(a["pw"]), groups=int(a.get("groups", 1)),
                    use_bias=bool(int(a.get("bias", 1))), name=d.name)
            elif d.op_type == "linear":
                values[d.name] = ffmodel.dense(
                    values[d.inputs[0]], int(a["out"]),
                    use_bias=bool(int(a.get("bias", 1))), name=d.name)
            elif d.op_type == "batch_norm":
                values[d.name] = ffmodel.batch_norm(
                    values[d.inputs[0]], relu=False, name=d.name)
            elif d.op_type == "layer_norm":
                values[d.name] = ffmodel.layer_norm(
                    values[d.inputs[0]], eps=float(a.get("eps", 1e-5)),
                    elementwise_affine=bool(int(a.get("affine", 1))),
                    name=d.name)
            elif d.op_type == "pool2d":
                k, s, p = int(a["k"]), int(a["s"]), int(a["p"])
                values[d.name] = ffmodel.pool2d(
                    values[d.inputs[0]], k, k, s, s, p, p,
                    pool_type=a.get("kind", "max"), name=d.name)
            elif d.op_type in ("relu", "sigmoid", "tanh", "gelu"):
                values[d.name] = getattr(ffmodel, d.op_type)(
                    values[d.inputs[0]], name=d.name)
            elif d.op_type == "softmax":
                values[d.name] = ffmodel.softmax(values[d.inputs[0]],
                                                 name=d.name)
            elif d.op_type == "dropout":
                values[d.name] = ffmodel.dropout(
                    values[d.inputs[0]], float(a.get("rate", 0.5)),
                    name=d.name)
            elif d.op_type == "flat":
                values[d.name] = ffmodel.flat(values[d.inputs[0]],
                                              name=d.name)
            elif d.op_type == "embedding":
                values[d.name] = ffmodel.embedding(
                    values[d.inputs[0]], int(a["vocab"]), int(a["dim"]),
                    aggr="none", name=d.name)
            elif d.op_type == "reduce_mean":
                # Reduce.__init__ normalizes negative axes and rejects
                # the sample dim — pass the raw axis through
                values[d.name] = ffmodel.reduce_mean(
                    values[d.inputs[0]], axis=int(a["axis"]),
                    keepdims=bool(int(a.get("keepdims", 0))),
                    name=d.name)
            elif d.op_type == "reshape":
                shape = [int(x) for x in str(a["shape"]).split(",")]
                values[d.name] = ffmodel.reshape(values[d.inputs[0]],
                                                 shape, name=d.name)
            elif d.op_type == "transpose":
                nd = len(values[d.inputs[0]].shape)
                perm = list(range(nd))
                d0, d1 = int(a["d0"]), int(a["d1"])
                perm[d0], perm[d1] = perm[d1], perm[d0]
                values[d.name] = ffmodel.transpose(values[d.inputs[0]],
                                                   perm, name=d.name)
            elif d.op_type in ("add", "subtract", "multiply", "divide"):
                values[d.name] = getattr(ffmodel, d.op_type)(
                    values[d.inputs[0]], values[d.inputs[1]], name=d.name)
            elif d.op_type == "concat":
                values[d.name] = ffmodel.concat(
                    [values[i] for i in d.inputs],
                    axis=int(a.get("axis", 1)), name=d.name)
            else:
                raise NotImplementedError(d.op_type)
        return outputs

    def import_weights(self, ffmodel) -> None:
        """Copy torch parameters into the compiled FFModel (layout
        translation: torch Linear (out,in) -> ours (in,out); Conv OIHW
        matches)."""
        assert self.module is not None, "need a live module for weights"
        assert ffmodel.state is not None, "compile the FFModel first"
        modules = dict(self.module.named_modules())
        for d in self.descs:
            target = d.attrs.get("target")
            if target is None or d.name not in ffmodel.state.params:
                continue
            m = modules[str(target)]
            w = {}
            if isinstance(m, nn.Linear):
                w["kernel"] = m.weight.detach().numpy().T
                if m.bias is not None:
                    w["bias"] = m.bias.detach().numpy()
            elif isinstance(m, nn.Conv2d):
                w["kernel"] = m.weight.detach().numpy()
                if m.bias is not None:
                    w["bias"] = m.bias.detach().numpy()
            elif isinstance(m, nn.BatchNorm2d):
                w["scale"] = m.weight.detach().numpy()
                w["bias"] = m.bias.detach().numpy()
            elif isinstance(m, nn.Embedding):
                w["kernel"] = m.weight.detach().numpy()
            elif isinstance(m, nn.LayerNorm):
                if m.elementwise_affine:
                    w["scale"] = m.weight.detach().numpy()
                    w["bias"] = m.bias.detach().numpy()
            if w:
                ffmodel.set_weights(d.name, w)


def _maybe_num(v):
    return v
