"""ONNX importer.

Reference: python/flexflow/onnx/model.py — `ONNXModel.apply(ffmodel,
input_dict)` with per-node handlers (Conv, Gemm->dense, MaxPool/
AveragePool, BatchNormalization, Concat, Split, Flatten, Relu, Softmax,
Reshape, Add/Sub/Mul, Dropout; onnx/model.py:74-340).

The handler table operates on a neutral node form (`GraphNode`:
op_type/input/output/name + plain-dict attrs), so it is fully
executable without the `onnx` package: `ONNXModel.from_graph(nodes,
initializers)` builds one directly (used by tests and any frontend
that can produce the node list). Loading a real `.onnx` file/proto
still requires `onnx` and is gated per-call.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence

import numpy as np

try:
    import onnx
    from onnx import numpy_helper
    HAS_ONNX = True
except ImportError:  # pragma: no cover - onnx absent in CI image
    HAS_ONNX = False


@dataclass
class GraphNode:
    """Neutral ONNX node: what the handlers consume."""
    op_type: str
    input: List[str]
    output: List[str]
    name: str = ""
    attrs: Dict = field(default_factory=dict)


def _sym_pads(attrs, node):
    """ONNX pads are [h_begin, w_begin, h_end, w_end]; the framework's
    conv/pool take symmetric padding only — reject asymmetric pads loudly
    rather than silently dropping the end pads."""
    pads = attrs.get("pads", [0, 0, 0, 0])
    if len(pads) == 4 and (pads[0] != pads[2] or pads[1] != pads[3]):
        raise NotImplementedError(
            f"asymmetric ONNX padding {pads} on node "
            f"{node.name or node.output[0]} is unsupported")
    return pads


def _proto_attrs(node) -> Dict:
    out = {}
    for a in node.attribute:
        if a.type == onnx.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == onnx.AttributeProto.INTS:
            out[a.name] = list(a.ints)
        elif a.type == onnx.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == onnx.AttributeProto.STRING:
            out[a.name] = a.s.decode()
    return out


class ONNXModel:
    def __init__(self, path_or_model):
        if not HAS_ONNX:
            raise ImportError(
                "the `onnx` package is required to load .onnx files; "
                "pip install onnx (or build the graph with "
                "ONNXModel.from_graph)")
        model = (onnx.load(path_or_model)
                 if isinstance(path_or_model, str) else path_or_model)
        self.inits = {t.name: numpy_helper.to_array(t)
                      for t in model.graph.initializer}
        self.nodes = [GraphNode(n.op_type, list(n.input), list(n.output),
                                n.name, _proto_attrs(n))
                      for n in model.graph.node]

    @classmethod
    def from_graph(cls, nodes: Sequence[GraphNode],
                   initializers: Dict[str, np.ndarray]) -> "ONNXModel":
        """Build from pre-parsed nodes — no `onnx` dependency."""
        self = cls.__new__(cls)
        self.inits = dict(initializers)
        self.nodes = list(nodes)
        return self

    def apply(self, ffmodel, input_dict: Dict[str, "Tensor"]):
        """Emit the graph onto ffmodel; input_dict maps ONNX graph input
        names to framework tensors. Returns the output tensor.

        Trained initializer weights are staged on
        `ffmodel.imported_weights`/`imported_states` (applied by
        compile()); call `import_weights(ffmodel)` instead when the
        model is already compiled."""
        values = dict(input_dict)
        pending_weights: Dict[str, Dict[str, np.ndarray]] = {}
        pending_states: Dict[str, Dict[str, np.ndarray]] = {}
        out = None
        for node in self.nodes:
            a = node.attrs
            ins = node.input
            name = node.name or node.output[0]
            if node.op_type == "Conv":
                w = self.inits[ins[1]]
                bias = self.inits[ins[2]] if len(ins) > 2 else None
                kh, kw = a.get("kernel_shape", w.shape[2:])
                sh, sw = a.get("strides", [1, 1])
                pads = _sym_pads(a, node)
                t = ffmodel.conv2d(values[ins[0]], w.shape[0], kh, kw, sh,
                                   sw, pads[0], pads[1],
                                   groups=a.get("group", 1),
                                   use_bias=bias is not None, name=name)
                # ONNX Conv weight layout is OIHW == framework layout
                pending_weights[name] = {"kernel": w} | (
                    {"bias": bias} if bias is not None else {})
            elif node.op_type == "Gemm":
                w = self.inits[ins[1]]
                bias = self.inits[ins[2]] if len(ins) > 2 else None
                out_dim = w.shape[0] if a.get("transB", 0) else w.shape[1]
                t = ffmodel.dense(values[ins[0]], out_dim,
                                  use_bias=bias is not None, name=name)
                kernel = w.T if a.get("transB", 0) else w
                pending_weights[name] = {"kernel": kernel} | (
                    {"bias": bias} if bias is not None else {})
            elif node.op_type == "MatMul":
                w = self.inits.get(ins[1])
                if w is not None:
                    t = ffmodel.dense(values[ins[0]], w.shape[1],
                                      use_bias=False, name=name)
                    pending_weights[name] = {"kernel": w}
                else:
                    t = ffmodel.batch_matmul(values[ins[0]], values[ins[1]],
                                             name=name)
            elif node.op_type in ("MaxPool", "AveragePool"):
                kh, kw = a["kernel_shape"]
                sh, sw = a.get("strides", [kh, kw])
                pads = _sym_pads(a, node)
                t = ffmodel.pool2d(values[ins[0]], kh, kw, sh, sw,
                                   pads[0], pads[1],
                                   pool_type=("max" if node.op_type ==
                                              "MaxPool" else "avg"),
                                   name=name)
            elif node.op_type == "GlobalAveragePool":
                shp = values[ins[0]].shape
                t = ffmodel.pool2d(values[ins[0]], shp[2], shp[3], 1, 1,
                                   0, 0, pool_type="avg", name=name)
            elif node.op_type == "BatchNormalization":
                t = ffmodel.batch_norm(values[ins[0]], relu=False,
                                       name=name)
                pending_weights[name] = {"scale": self.inits[ins[1]],
                                         "bias": self.inits[ins[2]]}
                # inputs 3/4 = input_mean, input_var -> running stats
                if len(ins) > 4:
                    pending_states[name] = {
                        "running_mean": self.inits[ins[3]],
                        "running_var": self.inits[ins[4]]}
            elif node.op_type == "LayerNormalization":
                # opset-17 node: axis must be the last dim (the only
                # form the framework op supports)
                axis = a.get("axis", -1)
                rank = len(values[ins[0]].shape)
                if axis not in (-1, rank - 1):
                    raise NotImplementedError(
                        f"LayerNormalization axis={axis}; only last-dim "
                        f"normalization is supported")
                # Scale is a REQUIRED opset-17 input; like Conv/Gemm/BN
                # above, a non-initializer Scale fails loudly rather
                # than silently dropping the affine transform
                scale = self.inits[ins[1]]
                t = ffmodel.layer_norm(
                    values[ins[0]], eps=a.get("epsilon", 1e-5),
                    elementwise_affine=True, name=name)
                bias = (self.inits[ins[2]] if len(ins) > 2
                        else np.zeros_like(scale))
                pending_weights[name] = {"scale": scale, "bias": bias}
            elif node.op_type == "Concat":
                t = ffmodel.concat([values[i] for i in ins],
                                   axis=a.get("axis", 1), name=name)
            elif node.op_type == "Split":
                sizes = a.get("split")
                if sizes is None and len(ins) > 1:  # opset>=13: input 1
                    sizes = self.inits[ins[1]].tolist()
                if sizes is None:  # equal split into len(outputs)
                    sizes = len(node.output)
                outs = ffmodel.split(values[ins[0]], sizes,
                                     axis=a.get("axis", 0), name=name)
                for o_name, o_t in zip(node.output, outs):
                    values[o_name] = o_t
                continue
            elif node.op_type == "Flatten":
                t = ffmodel.flat(values[ins[0]], name=name)
            elif node.op_type == "Relu":
                t = ffmodel.relu(values[ins[0]], name=name)
            elif node.op_type == "Sigmoid":
                t = ffmodel.sigmoid(values[ins[0]], name=name)
            elif node.op_type == "Tanh":
                t = ffmodel.tanh(values[ins[0]], name=name)
            elif node.op_type == "Softmax":
                t = ffmodel.softmax(values[ins[0]], name=name)
            elif node.op_type == "Dropout":
                t = ffmodel.dropout(values[ins[0]], a.get("ratio", 0.5),
                                    name=name)
            elif node.op_type in ("Add", "Sub", "Mul", "Div"):
                mode = {"Add": "add", "Sub": "subtract", "Mul": "multiply",
                        "Div": "divide"}[node.op_type]
                t = getattr(ffmodel, mode)(values[ins[0]], values[ins[1]],
                                           name=name)
            elif node.op_type == "Reshape":
                shape = self.inits[ins[1]].tolist()
                t = ffmodel.reshape(values[ins[0]], shape, name=name)
            elif node.op_type == "Transpose":
                t = ffmodel.transpose(values[ins[0]], a["perm"], name=name)
            elif node.op_type == "Identity":
                t = values[ins[0]]
            else:
                raise NotImplementedError(
                    f"unsupported ONNX op {node.op_type}")
            values[node.output[0]] = t
            out = t
        self.pending_weights = pending_weights
        self.pending_states = pending_states
        # stage for compile(); harmless if import_weights is called instead
        ffmodel.imported_weights.update(
            {k: {n: np.asarray(v) for n, v in w.items()}
             for k, w in pending_weights.items()})
        ffmodel.imported_states.update(
            {k: {n: np.asarray(v) for n, v in s.items()}
             for k, s in pending_states.items()})
        return out

    def import_weights(self, ffmodel) -> None:
        """Apply pending weights to an already-compiled model."""
        for name, w in self.pending_weights.items():
            ffmodel.set_weights(name, {k: np.asarray(v)
                                       for k, v in w.items()})
        for name, s in self.pending_states.items():
            ffmodel.set_states(name, {k: np.asarray(v)
                                      for k, v in s.items()})
