"""ONNX importer.

Reference: python/flexflow/onnx/model.py — `ONNXModel.apply(ffmodel,
input_dict)` with per-node handlers (Conv, Gemm->dense, MaxPool/
AveragePool, BatchNormalization, Concat, Split, Flatten, Relu, Softmax,
Reshape, Add/Sub/Mul, Dropout; onnx/model.py:74-340).

Gated on the `onnx` package (not in this image's environment); the
handler table is complete so it activates wherever onnx is installed.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

try:
    import onnx
    from onnx import numpy_helper
    HAS_ONNX = True
except ImportError:  # pragma: no cover - onnx absent in CI image
    HAS_ONNX = False


def _sym_pads(attrs, node):
    """ONNX pads are [h_begin, w_begin, h_end, w_end]; the framework's
    conv/pool take symmetric padding only — reject asymmetric pads loudly
    rather than silently dropping the end pads."""
    pads = attrs.get("pads", [0, 0, 0, 0])
    if len(pads) == 4 and (pads[0] != pads[2] or pads[1] != pads[3]):
        raise NotImplementedError(
            f"asymmetric ONNX padding {pads} on node "
            f"{node.name or node.output[0]} is unsupported")
    return pads


class ONNXModel:
    def __init__(self, path_or_model):
        if not HAS_ONNX:
            raise ImportError(
                "the `onnx` package is required for the ONNX importer; "
                "pip install onnx")
        self.model = (onnx.load(path_or_model)
                      if isinstance(path_or_model, str) else path_or_model)
        self.inits = {t.name: numpy_helper.to_array(t)
                      for t in self.model.graph.initializer}

    @staticmethod
    def _attrs(node) -> Dict:
        out = {}
        for a in node.attribute:
            if a.type == onnx.AttributeProto.INT:
                out[a.name] = a.i
            elif a.type == onnx.AttributeProto.INTS:
                out[a.name] = list(a.ints)
            elif a.type == onnx.AttributeProto.FLOAT:
                out[a.name] = a.f
            elif a.type == onnx.AttributeProto.STRING:
                out[a.name] = a.s.decode()
        return out

    def apply(self, ffmodel, input_dict: Dict[str, "Tensor"]):
        """Emit the graph onto ffmodel; input_dict maps ONNX graph input
        names to framework tensors. Returns the output tensor."""
        values = dict(input_dict)
        pending_weights: Dict[str, Dict[str, np.ndarray]] = {}
        out = None
        for node in self.model.graph.node:
            a = self._attrs(node)
            ins = node.input
            name = node.name or node.output[0]
            if node.op_type == "Conv":
                w = self.inits[ins[1]]
                bias = self.inits[ins[2]] if len(ins) > 2 else None
                kh, kw = a.get("kernel_shape", w.shape[2:])
                sh, sw = a.get("strides", [1, 1])
                pads = _sym_pads(a, node)
                t = ffmodel.conv2d(values[ins[0]], w.shape[0], kh, kw, sh,
                                   sw, pads[0], pads[1],
                                   groups=a.get("group", 1),
                                   use_bias=bias is not None, name=name)
                pending_weights[name] = {"kernel": w} | (
                    {"bias": bias} if bias is not None else {})
            elif node.op_type == "Gemm":
                w = self.inits[ins[1]]
                bias = self.inits[ins[2]] if len(ins) > 2 else None
                out_dim = w.shape[0] if a.get("transB", 0) else w.shape[1]
                t = ffmodel.dense(values[ins[0]], out_dim,
                                  use_bias=bias is not None, name=name)
                kernel = w.T if a.get("transB", 0) else w
                pending_weights[name] = {"kernel": kernel} | (
                    {"bias": bias} if bias is not None else {})
            elif node.op_type == "MatMul":
                w = self.inits.get(ins[1])
                if w is not None:
                    t = ffmodel.dense(values[ins[0]], w.shape[1],
                                      use_bias=False, name=name)
                    pending_weights[name] = {"kernel": w}
                else:
                    t = ffmodel.batch_matmul(values[ins[0]], values[ins[1]],
                                             name=name)
            elif node.op_type in ("MaxPool", "AveragePool"):
                kh, kw = a["kernel_shape"]
                sh, sw = a.get("strides", [kh, kw])
                pads = _sym_pads(a, node)
                t = ffmodel.pool2d(values[ins[0]], kh, kw, sh, sw,
                                   pads[0], pads[1],
                                   pool_type=("max" if node.op_type ==
                                              "MaxPool" else "avg"),
                                   name=name)
            elif node.op_type == "GlobalAveragePool":
                shp = values[ins[0]].shape
                t = ffmodel.pool2d(values[ins[0]], shp[2], shp[3], 1, 1,
                                   0, 0, pool_type="avg", name=name)
            elif node.op_type == "BatchNormalization":
                t = ffmodel.batch_norm(values[ins[0]], relu=False,
                                       name=name)
                pending_weights[name] = {"scale": self.inits[ins[1]],
                                         "bias": self.inits[ins[2]]}
            elif node.op_type == "Concat":
                t = ffmodel.concat([values[i] for i in ins],
                                   axis=a.get("axis", 1), name=name)
            elif node.op_type == "Split":
                sizes = a.get("split")
                if sizes is None and len(ins) > 1:  # opset>=13: input 1
                    sizes = self.inits[ins[1]].tolist()
                if sizes is None:  # equal split into len(outputs)
                    sizes = len(node.output)
                outs = ffmodel.split(values[ins[0]], sizes,
                                     axis=a.get("axis", 0), name=name)
                for o_name, o_t in zip(node.output, outs):
                    values[o_name] = o_t
                continue
            elif node.op_type == "Flatten":
                t = ffmodel.flat(values[ins[0]], name=name)
            elif node.op_type == "Relu":
                t = ffmodel.relu(values[ins[0]], name=name)
            elif node.op_type == "Sigmoid":
                t = ffmodel.sigmoid(values[ins[0]], name=name)
            elif node.op_type == "Tanh":
                t = ffmodel.tanh(values[ins[0]], name=name)
            elif node.op_type == "Softmax":
                t = ffmodel.softmax(values[ins[0]], name=name)
            elif node.op_type == "Dropout":
                t = ffmodel.dropout(values[ins[0]], a.get("ratio", 0.5),
                                    name=name)
            elif node.op_type in ("Add", "Sub", "Mul", "Div"):
                mode = {"Add": "add", "Sub": "subtract", "Mul": "multiply",
                        "Div": "divide"}[node.op_type]
                t = getattr(ffmodel, mode)(values[ins[0]], values[ins[1]],
                                           name=name)
            elif node.op_type == "Reshape":
                shape = self.inits[ins[1]].tolist()
                t = ffmodel.reshape(values[ins[0]], shape, name=name)
            elif node.op_type == "Transpose":
                t = ffmodel.transpose(values[ins[0]], a["perm"], name=name)
            elif node.op_type == "Identity":
                t = values[ins[0]]
            else:
                raise NotImplementedError(
                    f"unsupported ONNX op {node.op_type}")
            values[node.output[0]] = t
            out = t
        self.pending_weights = pending_weights
        return out

    def import_weights(self, ffmodel) -> None:
        for name, w in self.pending_weights.items():
            ffmodel.set_weights(name, {k: np.asarray(v)
                                       for k, v in w.items()})
