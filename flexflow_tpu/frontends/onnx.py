"""ONNX importer.

Reference: python/flexflow/onnx/model.py — `ONNXModel.apply(ffmodel,
input_dict)` with per-node handlers (Conv, Gemm->dense, MaxPool/
AveragePool, BatchNormalization, Concat, Split, Flatten, Relu, Softmax,
Reshape, Add/Sub/Mul, Dropout; onnx/model.py:74-340).

The handler table operates on a neutral node form (`GraphNode`:
op_type/input/output/name + plain-dict attrs). Real `.onnx` files load
with ZERO dependencies: when the `onnx` package is absent, the wire
format is read by the in-tree protobuf decoder (`onnx_wire.py` —
nodes, attributes, tensor initializers incl. raw_data).
`ONNXModel.from_graph(nodes, initializers)` additionally accepts a
pre-parsed node list from any producer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence

import numpy as np

try:
    import onnx
    from onnx import numpy_helper
    HAS_ONNX = True
except ImportError:  # pragma: no cover - onnx absent in CI image
    HAS_ONNX = False


@dataclass
class GraphNode:
    """Neutral ONNX node: what the handlers consume."""
    op_type: str
    input: List[str]
    output: List[str]
    name: str = ""
    attrs: Dict = field(default_factory=dict)


def _sym_pads(attrs, node):
    """ONNX pads are [h_begin, w_begin, h_end, w_end]; the framework's
    conv/pool take symmetric padding only — reject asymmetric pads loudly
    rather than silently dropping the end pads."""
    pads = attrs.get("pads", [0, 0, 0, 0])
    if len(pads) == 4 and (pads[0] != pads[2] or pads[1] != pads[3]):
        raise NotImplementedError(
            f"asymmetric ONNX padding {pads} on node "
            f"{node.name or node.output[0]} is unsupported")
    return pads


def _proto_attrs(node) -> Dict:
    out = {}
    for a in node.attribute:
        if a.type == onnx.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == onnx.AttributeProto.INTS:
            out[a.name] = list(a.ints)
        elif a.type == onnx.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == onnx.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == onnx.AttributeProto.TENSOR:
            # Constant nodes carry their payload here; the wire decoder
            # path decodes these too — keep both loaders equivalent
            out[a.name] = numpy_helper.to_array(a.t)
    return out


def _input_dtype(name: str, elem_type: int) -> np.dtype:
    """Graph-input elem_type -> numpy dtype. 0 (unset) defaults to f32;
    a SET-but-unsupported type (bfloat16/float8/...) fails loudly like
    initializer decoding does — a silent f32 input would train wrong."""
    from .onnx_wire import TENSOR_DTYPES
    if elem_type == 0:
        return np.dtype(np.float32)
    if elem_type not in TENSOR_DTYPES:
        raise NotImplementedError(
            f"graph input {name!r}: elem_type {elem_type} is "
            f"unsupported (bfloat16/float8 inputs need explicit "
            f"tensors passed to apply())")
    return np.dtype(TENSOR_DTYPES[elem_type])


def export_torch_onnx(module, args, path, **kw) -> None:
    """torch.onnx.export that works WITHOUT the `onnx` package: the
    TorchScript exporter serializes the ModelProto in C++; only its
    onnxscript post-processing step re-parses with `onnx`, and that
    step is a no-op for plain nn modules — skip it when onnx is absent.
    (Reference keras_exp/onnx flows assume onnx is installed; here the
    zero-dep path keeps the frontend testable in the base image.)"""
    import torch
    if HAS_ONNX:
        torch.onnx.export(module, args, path, dynamo=False, **kw)
        return
    try:
        from torch.onnx._internal.torchscript_exporter import (
            onnx_proto_utils,
        )
    except ImportError as e:  # pragma: no cover - torch layout changed
        raise ImportError(
            "torch.onnx internals moved; install the `onnx` package to "
            "export") from e
    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda b, c: b
    try:
        torch.onnx.export(module, args, path, dynamo=False, **kw)
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


class ONNXModel:
    def __init__(self, path_or_model):
        # [(name, shape, np dtype)] for non-initializer graph inputs
        self.graph_inputs = []
        if HAS_ONNX and not isinstance(path_or_model, (str, bytes)):
            model = path_or_model  # an onnx.ModelProto object
        elif HAS_ONNX:
            model = (onnx.load_model_from_string(path_or_model)
                     if isinstance(path_or_model, bytes)
                     else onnx.load(path_or_model))
        else:
            # no onnx package: read the wire format directly
            from .onnx_wire import load_model
            parsed = load_model(path_or_model)
            g = parsed["graph"]
            self.inits = dict(g["initializers"])
            self.nodes = [GraphNode(n["op_type"], n["input"], n["output"],
                                    n["name"], n["attrs"])
                          for n in g["nodes"]]
            self.graph_inputs = [
                (vi["name"], vi["shape"],
                 _input_dtype(vi["name"], vi["elem_type"]))
                for vi in g["inputs"] if vi["name"] not in self.inits]
            return
        self.inits = {t.name: numpy_helper.to_array(t)
                      for t in model.graph.initializer}
        self.nodes = [GraphNode(n.op_type, list(n.input), list(n.output),
                                n.name, _proto_attrs(n))
                      for n in model.graph.node]
        self.graph_inputs = [
            (vi.name,
             [d.dim_value or d.dim_param
              for d in vi.type.tensor_type.shape.dim],
             _input_dtype(vi.name, vi.type.tensor_type.elem_type))
            for vi in model.graph.input if vi.name not in self.inits]

    @classmethod
    def from_graph(cls, nodes: Sequence[GraphNode],
                   initializers: Dict[str, np.ndarray]) -> "ONNXModel":
        """Build from pre-parsed nodes — no `onnx` dependency."""
        self = cls.__new__(cls)
        self.inits = dict(initializers)
        self.nodes = list(nodes)
        self.graph_inputs = []
        return self

    def make_input_tensors(self, ffmodel, batch_size: int = None,
                           dtype=None) -> Dict[str, "Tensor"]:
        """Create framework input tensors from the graph's declared
        (non-initializer) inputs — the dict `apply` consumes, with each
        input's ONNX elem_type as its dtype (int64 ids build int
        tensors, not f32). Dim 0 is replaced by `batch_size` when
        given; symbolic dims elsewhere fail loudly (provide tensors by
        hand for dynamic graphs). `dtype` overrides every input."""
        out = {}
        for name, shape, in_dtype in self.graph_inputs:
            shape = list(shape)
            if batch_size is not None and shape:
                shape[0] = batch_size
            if any(not isinstance(d, int) or d <= 0 for d in shape):
                raise ValueError(
                    f"graph input {name!r} has non-static shape {shape}; "
                    f"pass an explicit tensor to apply() instead")
            in_dtype = np.dtype(in_dtype)
            # JAX (x64 disabled) holds 32-bit ints/floats; declare the
            # dtype arrays will ACTUALLY have instead of letting the
            # backend truncate with a warning (ids are int32 on device —
            # embedding forward casts anyway)
            narrow = {np.dtype(np.int64): np.dtype(np.int32),
                      np.dtype(np.uint64): np.dtype(np.uint32),
                      np.dtype(np.float64): np.dtype(np.float32)}
            in_dtype = narrow.get(in_dtype, in_dtype)
            out[name] = ffmodel.create_tensor(
                tuple(shape), name=name, dtype=dtype or in_dtype)
        return out

    def apply(self, ffmodel, input_dict: Dict[str, "Tensor"]):
        """Emit the graph onto ffmodel; input_dict maps ONNX graph input
        names to framework tensors. Returns the output tensor.

        Trained initializer weights are staged on
        `ffmodel.imported_weights`/`imported_states` (applied by
        compile()); call `import_weights(ffmodel)` instead when the
        model is already compiled."""
        values = dict(input_dict)
        pending_weights: Dict[str, Dict[str, np.ndarray]] = {}
        pending_states: Dict[str, Dict[str, np.ndarray]] = {}
        out = None
        for node in self.nodes:
            a = node.attrs
            ins = node.input
            name = node.name or node.output[0]
            if node.op_type == "Conv":
                w = self.inits[ins[1]]
                bias = self.inits[ins[2]] if len(ins) > 2 else None
                kh, kw = a.get("kernel_shape", w.shape[2:])
                sh, sw = a.get("strides", [1, 1])
                pads = _sym_pads(a, node)
                t = ffmodel.conv2d(values[ins[0]], w.shape[0], kh, kw, sh,
                                   sw, pads[0], pads[1],
                                   groups=a.get("group", 1),
                                   use_bias=bias is not None, name=name)
                # ONNX Conv weight layout is OIHW == framework layout
                pending_weights[name] = {"kernel": w} | (
                    {"bias": bias} if bias is not None else {})
            elif node.op_type == "Gemm":
                w = self.inits[ins[1]]
                bias = self.inits[ins[2]] if len(ins) > 2 else None
                out_dim = w.shape[0] if a.get("transB", 0) else w.shape[1]
                t = ffmodel.dense(values[ins[0]], out_dim,
                                  use_bias=bias is not None, name=name)
                kernel = w.T if a.get("transB", 0) else w
                pending_weights[name] = {"kernel": kernel} | (
                    {"bias": bias} if bias is not None else {})
            elif node.op_type == "MatMul":
                w = self.inits.get(ins[1])
                if w is not None:
                    t = ffmodel.dense(values[ins[0]], w.shape[1],
                                      use_bias=False, name=name)
                    pending_weights[name] = {"kernel": w}
                else:
                    t = ffmodel.batch_matmul(values[ins[0]], values[ins[1]],
                                             name=name)
            elif node.op_type in ("MaxPool", "AveragePool"):
                kh, kw = a["kernel_shape"]
                sh, sw = a.get("strides", [kh, kw])
                pads = _sym_pads(a, node)
                t = ffmodel.pool2d(values[ins[0]], kh, kw, sh, sw,
                                   pads[0], pads[1],
                                   pool_type=("max" if node.op_type ==
                                              "MaxPool" else "avg"),
                                   name=name)
            elif node.op_type == "GlobalAveragePool":
                shp = values[ins[0]].shape
                t = ffmodel.pool2d(values[ins[0]], shp[2], shp[3], 1, 1,
                                   0, 0, pool_type="avg", name=name)
            elif node.op_type == "BatchNormalization":
                t = ffmodel.batch_norm(values[ins[0]], relu=False,
                                       name=name)
                pending_weights[name] = {"scale": self.inits[ins[1]],
                                         "bias": self.inits[ins[2]]}
                # inputs 3/4 = input_mean, input_var -> running stats
                if len(ins) > 4:
                    pending_states[name] = {
                        "running_mean": self.inits[ins[3]],
                        "running_var": self.inits[ins[4]]}
            elif node.op_type == "LayerNormalization":
                # opset-17 node: axis must be the last dim (the only
                # form the framework op supports)
                axis = a.get("axis", -1)
                rank = len(values[ins[0]].shape)
                if axis not in (-1, rank - 1):
                    raise NotImplementedError(
                        f"LayerNormalization axis={axis}; only last-dim "
                        f"normalization is supported")
                # Scale is a REQUIRED opset-17 input; like Conv/Gemm/BN
                # above, a non-initializer Scale fails loudly rather
                # than silently dropping the affine transform
                scale = self.inits[ins[1]]
                t = ffmodel.layer_norm(
                    values[ins[0]], eps=a.get("epsilon", 1e-5),
                    elementwise_affine=True, name=name)
                bias = (self.inits[ins[2]] if len(ins) > 2
                        else np.zeros_like(scale))
                pending_weights[name] = {"scale": scale, "bias": bias}
            elif node.op_type == "Concat":
                t = ffmodel.concat([values[i] for i in ins],
                                   axis=a.get("axis", 1), name=name)
            elif node.op_type == "Split":
                sizes = a.get("split")
                if sizes is None and len(ins) > 1:  # opset>=13: input 1
                    sizes = self.inits[ins[1]].tolist()
                if sizes is None:  # equal split into len(outputs)
                    sizes = len(node.output)
                outs = ffmodel.split(values[ins[0]], sizes,
                                     axis=a.get("axis", 0), name=name)
                for o_name, o_t in zip(node.output, outs):
                    values[o_name] = o_t
                continue
            elif node.op_type == "Flatten":
                t = ffmodel.flat(values[ins[0]], name=name)
            elif node.op_type == "Relu":
                t = ffmodel.relu(values[ins[0]], name=name)
            elif node.op_type == "Sigmoid":
                t = ffmodel.sigmoid(values[ins[0]], name=name)
            elif node.op_type == "Tanh":
                t = ffmodel.tanh(values[ins[0]], name=name)
            elif node.op_type == "Softmax":
                t = ffmodel.softmax(values[ins[0]], name=name)
            elif node.op_type == "Dropout":
                t = ffmodel.dropout(values[ins[0]], a.get("ratio", 0.5),
                                    name=name)
            elif node.op_type in ("Add", "Sub", "Mul", "Div"):
                mode = {"Add": "add", "Sub": "subtract", "Mul": "multiply",
                        "Div": "divide"}[node.op_type]
                t = getattr(ffmodel, mode)(values[ins[0]], values[ins[1]],
                                           name=name)
            elif node.op_type == "Gather":
                # torch exports nn.Embedding as Gather(table, ids) on
                # axis 0 — lower to the embedding op (aggr="none")
                w = self.inits.get(ins[0])
                if w is None or a.get("axis", 0) != 0 or w.ndim != 2:
                    raise NotImplementedError(
                        f"Gather node {name}: only axis-0 gathers from a "
                        f"2-D initializer (embedding tables) are "
                        f"supported")
                t = ffmodel.embedding(values[ins[1]], w.shape[0],
                                      w.shape[1], aggr="none", name=name)
                pending_weights[name] = {"kernel": w}
            elif node.op_type in ("ReduceMean", "ReduceSum", "ReduceMax"):
                axes = a.get("axes")
                if axes is None and len(ins) > 1:  # opset>=18: input 1
                    ax_init = self.inits.get(ins[1])
                    if ax_init is None:
                        raise NotImplementedError(
                            f"{node.op_type} node {name}: axes must be a "
                            f"constant (initializer/Constant); dynamically "
                            f"computed axes are unsupported")
                    axes = ax_init.tolist()
                if axes is None or len(list(np.ravel(axes))) != 1:
                    raise NotImplementedError(
                        f"{node.op_type} node {name}: exactly one axis "
                        f"is supported, got {axes}")
                fn = {"ReduceMean": ffmodel.reduce_mean,
                      "ReduceSum": ffmodel.reduce_sum,
                      "ReduceMax": ffmodel.reduce_max}[node.op_type]
                t = fn(values[ins[0]], axis=int(np.ravel(axes)[0]),
                       keepdims=bool(a.get("keepdims", 1)), name=name)
            elif node.op_type == "Constant":
                # fold into the initializer map: downstream handlers
                # (Reshape shape, Split sizes) read constants from there
                val = a.get("value")
                if val is None:
                    raise NotImplementedError(
                        f"Constant node {name} without a tensor `value` "
                        f"attribute")
                self.inits[node.output[0]] = np.asarray(val)
                continue
            elif node.op_type == "Reshape":
                shape = self.inits[ins[1]].tolist()
                t = ffmodel.reshape(values[ins[0]], shape, name=name)
            elif node.op_type == "Transpose":
                t = ffmodel.transpose(values[ins[0]], a["perm"], name=name)
            elif node.op_type == "Identity":
                if ins[0] in self.inits and ins[0] not in values:
                    # torch's BN-folding export aliases a shared
                    # initializer to one Identity per consumer; keep it
                    # an initializer so Conv/Gemm read it as a weight
                    self.inits[node.output[0]] = self.inits[ins[0]]
                    continue
                t = values[ins[0]]
            else:
                raise NotImplementedError(
                    f"unsupported ONNX op {node.op_type}")
            values[node.output[0]] = t
            out = t
        self.pending_weights = pending_weights
        self.pending_states = pending_states
        # stage for compile(); harmless if import_weights is called instead
        ffmodel.imported_weights.update(
            {k: {n: np.asarray(v) for n, v in w.items()}
             for k, w in pending_weights.items()})
        ffmodel.imported_states.update(
            {k: {n: np.asarray(v) for n, v in s.items()}
             for k, s in pending_states.items()})
        return out

    def import_weights(self, ffmodel) -> None:
        """Apply pending weights to an already-compiled model."""
        for name, w in self.pending_weights.items():
            ffmodel.set_weights(name, {k: np.asarray(v)
                                       for k, v in w.items()})
        for name, s in self.pending_states.items():
            ffmodel.set_states(name, {k: np.asarray(v)
                                      for k, v in s.items()})
