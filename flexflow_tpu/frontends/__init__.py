"""Frontends: Keras-compatible API, ONNX importer, PyTorch fx importer —
the TPU-native equivalents of reference python/flexflow/{keras,onnx,torch}
(SURVEY.md 2.6)."""
