"""Pure-Python ONNX protobuf wire-format reader.

Reference: python/flexflow/onnx/model.py consumes the `onnx` package's
generated protobuf bindings. That package is not a dependency here, so
this module reads the ONNX wire format directly — a minimal protobuf
decoder over the PUBLIC onnx.proto3 schema (field numbers below are the
schema's, stable by protobuf compatibility rules) covering what the
importer needs: ModelProto -> GraphProto -> nodes (op_type, inputs,
outputs, attributes), initializers (TensorProto with raw_data or packed
typed data), and graph inputs with static shapes.

Protobuf wire format: each field is a varint tag `(field_no << 3) |
wire_type`; wire types: 0 = varint, 1 = 64-bit, 2 = length-delimited
(submessages, strings, packed repeated scalars), 5 = 32-bit.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

# --- generic protobuf scanning -----------------------------------------


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated protobuf: buffer ends mid-varint")
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _fields(buf: bytes):
    """Yield (field_no, wire_type, value); value is int (wire 0/1/5 —
    1/5 returned as raw little-endian ints) or bytes (wire 2)."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _varint(buf, pos)
        field_no, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _varint(buf, pos)
        elif wt == 1:
            val = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wt == 5:
            val = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            if ln > n - pos:
                # a silent short slice would drop trailing nodes/
                # initializers of a truncated download — fail loudly
                raise ValueError(
                    f"truncated protobuf: field {field_no} declares "
                    f"{ln} bytes, {n - pos} remain")
            val = buf[pos:pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wt} (group fields "
                             f"were removed from proto3)")
        yield field_no, wt, val


def _signed(v: int) -> int:
    """int64 varints are two's-complement on the wire."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _f32(v: int) -> float:
    return struct.unpack("<f", v.to_bytes(4, "little"))[0]


def _packed_or_scalar(acc: list, wt, val, fmt=None, unsigned=False):
    """Repeated scalar field: packed (wire 2) or one-per-entry; `fmt`
    set for fixed-width (float/double) elements, varints otherwise.
    `unsigned` skips the two's-complement reinterpretation (uint64_data
    values >= 2^63 are NOT negative int64s)."""
    conv = (lambda v: v) if unsigned else _signed
    if wt == 2:
        if fmt:  # fixed-width packed
            acc.extend(x[0] for x in struct.iter_unpack(fmt, val))
        else:  # packed varints
            pos = 0
            while pos < len(val):
                v, pos = _varint(val, pos)
                acc.append(conv(v))
    elif fmt:
        acc.append(struct.unpack(fmt, val.to_bytes(
            8 if fmt[1] in "dq" else 4, "little"))[0])
    else:
        acc.append(conv(val))


# --- ONNX messages -----------------------------------------------------

# TensorProto.DataType -> numpy dtype (onnx.proto3 enum)
TENSOR_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
    11: np.float64, 12: np.uint32, 13: np.uint64,
}


def parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    """TensorProto: dims=1, data_type=2, float_data=4, int32_data=5,
    string_data=6, int64_data=7, name=8, raw_data=9, double_data=10,
    uint64_data=11."""
    dims: List[int] = []
    data_type = 0
    name = ""
    raw = None
    floats: list = []
    i32: list = []
    i64: list = []
    f64: list = []
    u64: list = []
    for fno, wt, val in _fields(buf):
        if fno == 1:
            _packed_or_scalar(dims, wt, val)
        elif fno == 2:
            data_type = val
        elif fno == 4:
            _packed_or_scalar(floats, wt, val, "<f")
        elif fno == 5:
            _packed_or_scalar(i32, wt, val)
        elif fno == 7:
            _packed_or_scalar(i64, wt, val)
        elif fno == 8:
            name = val.decode()
        elif fno == 9:
            raw = bytes(val)
        elif fno == 10:
            _packed_or_scalar(f64, wt, val, "<d")
        elif fno == 11:
            _packed_or_scalar(u64, wt, val, unsigned=True)
        elif fno == 6:
            raise NotImplementedError(
                f"ONNX string tensors are unsupported ({name!r})")
    if data_type not in TENSOR_DTYPES:
        raise NotImplementedError(
            f"ONNX tensor {name!r}: data_type {data_type} unsupported "
            f"(bfloat16/string/complex need the onnx package)")
    dtype = np.dtype(TENSOR_DTYPES[data_type])
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype.newbyteorder("<"))
        arr = arr.astype(dtype)
    elif floats:
        arr = np.asarray(floats, np.float32).astype(dtype)
    elif i64:
        arr = np.asarray(i64, np.int64).astype(dtype)
    elif i32:
        # int32_data also carries (u)int8/16/bool/float16 per the schema
        base = np.asarray(i32, np.int32)
        arr = (base.astype(np.uint16).view(np.float16)
               if dtype == np.float16 else base.astype(dtype))
    elif f64:
        arr = np.asarray(f64, np.float64).astype(dtype)
    elif u64:
        arr = np.asarray(u64, np.uint64).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return name, arr.reshape([int(d) for d in dims])


def parse_attribute(buf: bytes):
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, g=6, floats=7,
    ints=8, strings=9, type=20. Returns (name, python value)."""
    name = ""
    atype = 0
    f = i = s = t = None
    floats: list = []
    ints: list = []
    strings: list = []
    for fno, wt, val in _fields(buf):
        if fno == 1:
            name = val.decode()
        elif fno == 2:
            f = _f32(val)
        elif fno == 3:
            i = _signed(val)
        elif fno == 4:
            s = val
        elif fno == 5:
            t = parse_tensor(val)[1]
        elif fno == 6:
            raise NotImplementedError(
                f"ONNX attribute {name!r}: GRAPH attributes (If/Loop "
                f"subgraphs) are unsupported")
        elif fno == 7:
            _packed_or_scalar(floats, wt, val, "<f")
        elif fno == 8:
            _packed_or_scalar(ints, wt, val)
        elif fno == 9:
            strings.append(val)
        elif fno == 20:
            atype = val
    # AttributeProto.type disambiguates (FLOAT=1 INT=2 STRING=3 TENSOR=4
    # FLOATS=6 INTS=7 STRINGS=8); fall back to whichever field is set
    # for writers that omit it
    by_type = {1: f, 2: i, 3: s.decode() if s is not None else None,
               4: t, 6: floats, 7: ints,
               8: [x.decode() for x in strings]}
    if atype in by_type:
        return name, by_type[atype]
    if atype:  # set but outside the supported set (GRAPH(S)=5/10, etc.)
        raise NotImplementedError(
            f"ONNX attribute {name!r}: AttributeProto.type {atype} "
            f"unsupported")
    for v in (i, f, t):
        if v is not None:
            return name, v
    if s is not None:
        return name, s.decode()
    for v in (ints, floats):
        if v:
            return name, v
    if strings:
        return name, [x.decode() for x in strings]
    return name, None


def parse_node(buf: bytes) -> Dict:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    node = {"input": [], "output": [], "name": "", "op_type": "",
            "attrs": {}}
    for fno, wt, val in _fields(buf):
        if fno == 1:
            node["input"].append(val.decode())
        elif fno == 2:
            node["output"].append(val.decode())
        elif fno == 3:
            node["name"] = val.decode()
        elif fno == 4:
            node["op_type"] = val.decode()
        elif fno == 5:
            k, v = parse_attribute(val)
            node["attrs"][k] = v
    return node


def _parse_shape(buf: bytes) -> List:
    """TensorShapeProto: dim=1 (dim_value=1 | dim_param=2)."""
    dims = []
    for fno, _wt, val in _fields(buf):
        if fno == 1:
            d = None
            for f2, _w2, v2 in _fields(val):
                if f2 == 1:
                    d = _signed(v2)
                elif f2 == 2 and d is None:
                    d = v2.decode()  # symbolic dim
            dims.append(d)
    return dims


def _parse_value_info(buf: bytes) -> Dict:
    """ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1 with
    elem_type=1, shape=2."""
    out = {"name": "", "elem_type": 0, "shape": []}
    for fno, _wt, val in _fields(buf):
        if fno == 1:
            out["name"] = val.decode()
        elif fno == 2:
            for f2, _w2, v2 in _fields(val):
                if f2 == 1:  # tensor_type
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            out["elem_type"] = v3
                        elif f3 == 2:
                            out["shape"] = _parse_shape(v3)
    return out


def parse_graph(buf: bytes) -> Dict:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    g = {"nodes": [], "name": "", "initializers": {}, "inputs": [],
         "outputs": []}
    for fno, _wt, val in _fields(buf):
        if fno == 1:
            g["nodes"].append(parse_node(val))
        elif fno == 2:
            g["name"] = val.decode()
        elif fno == 5:
            name, arr = parse_tensor(val)
            g["initializers"][name] = arr
        elif fno == 11:
            g["inputs"].append(_parse_value_info(val))
        elif fno == 12:
            g["outputs"].append(_parse_value_info(val))
        elif fno == 15:
            raise NotImplementedError(
                "sparse_initializer needs the onnx package")
    return g


def parse_model(data: bytes) -> Dict:
    """ModelProto: ir_version=1, producer_name=2, graph=7,
    opset_import=8 (domain=1, version=2)."""
    model = {"ir_version": 0, "producer_name": "", "graph": None,
             "opset": {}}
    for fno, _wt, val in _fields(data):
        if fno == 1:
            model["ir_version"] = val
        elif fno == 2:
            model["producer_name"] = val.decode()
        elif fno == 7:
            model["graph"] = parse_graph(val)
        elif fno == 8:
            dom, ver = "", 0
            for f2, _w2, v2 in _fields(val):
                if f2 == 1:
                    dom = v2.decode()
                elif f2 == 2:
                    ver = v2
            model["opset"][dom] = ver
    if model["graph"] is None:
        raise ValueError("not an ONNX ModelProto: no graph field")
    return model


def load_model(path_or_bytes) -> Dict:
    """Read a .onnx file (or proto bytes) into the parsed-model dict."""
    if isinstance(path_or_bytes, bytes):
        return parse_model(path_or_bytes)
    with open(path_or_bytes, "rb") as f:
        return parse_model(f.read())
