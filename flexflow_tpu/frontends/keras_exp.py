"""Experimental frontend: import a REAL tf.keras model.

Reference: python/flexflow/keras_exp/models/model.py:36-424 — walks a
genuine tf.keras model object (rather than this package's Keras-clone
layer classes) and replays it onto the framework's builder API.

TensorFlow is not part of this image (zero egress), so the module is
import-gated: `HAS_TF` is False and `from_tf_keras` raises a clear
ImportError without TF. With TF present, supported layers mirror the
reference's handler set (Conv2D/Pooling/Dense/Flatten/Dropout/
BatchNormalization/Activation/Concatenate/Add/Embedding).
"""

from __future__ import annotations

from typing import Optional

try:
    import tensorflow as _tf  # noqa: F401
    HAS_TF = True
except Exception:  # pragma: no cover - image ships without TF
    _tf = None
    HAS_TF = False


def from_tf_keras(tf_model, config=None, batch_size: Optional[int] = None,
                  mesh=None, strategy=None):
    """Replay a tf.keras Model onto an FFModel; returns the FFModel.

    Layer coverage follows the reference keras_exp handler set; raises
    NotImplementedError on anything else so failures are explicit.
    """
    if not HAS_TF:
        raise ImportError(
            "flexflow_tpu.frontends.keras_exp requires tensorflow, which "
            "is not installed in this environment; use "
            "flexflow_tpu.frontends.keras (native clone) or "
            "frontends.onnx/torchfx instead")

    import numpy as np

    from ..config import FFConfig
    from ..model import FFModel

    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)

    values = {}  # tf tensor ref -> framework Tensor

    for inp in tf_model.inputs:
        shape = tuple(int(d) for d in inp.shape[1:])
        values[inp.ref()] = ff.create_tensor(
            (bs,) + shape, name=inp.name.split(":")[0])

    for layer in tf_model.layers:
        ltype = type(layer).__name__
        if ltype == "InputLayer":
            continue
        ins = [values[t.ref()] for t in _flat_inputs(layer)]
        out = _emit_layer(ff, layer, ltype, ins)
        for t in _flat_outputs(layer):
            values[t.ref()] = out

    # stage trained weights; FFModel.compile applies them after
    # init_state (state does not exist yet at this point)
    ops_by_name = {op.name: op for op in ff.ops}
    for layer in tf_model.layers:
        w = layer.get_weights()
        op = ops_by_name.get(layer.name)
        if not w or op is None:
            continue
        # pair each tf array with an unused same-shape framework weight
        # (tf.keras get_weights() order is [kernel, bias, ...]; our dict
        # order is arbitrary, so match by shape, not position)
        specs = op.weight_specs()
        mapped = {}
        unused = {n: s.shape for n, s in specs.items()}
        for tf_arr in w:
            hit = next((n for n, shape in unused.items()
                        if tuple(shape) == tuple(np.shape(tf_arr))), None)
            if hit is not None:
                mapped[hit] = np.asarray(tf_arr)
                del unused[hit]
        if mapped:
            ff.imported_weights[layer.name] = mapped
    return ff


def _flat_inputs(layer):
    x = layer.input
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _flat_outputs(layer):
    x = layer.output
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _emit_layer(ff, layer, ltype, ins):
    cfgd = layer.get_config()
    if ltype == "Dense":
        act = cfgd.get("activation")
        t = ff.dense(ins[0], cfgd["units"],
                     activation=None if act == "softmax" else _act(act),
                     use_bias=cfgd.get("use_bias", True), name=layer.name)
        if act == "softmax":
            t = ff.softmax(t, name=f"{layer.name}_softmax")
        return t
    if ltype == "Conv2D":
        kh, kw = cfgd["kernel_size"]
        sh, sw = cfgd["strides"]
        pad = _same_pad(cfgd["padding"], kh, kw, sh, sw, ltype)
        return ff.conv2d(ins[0], cfgd["filters"], kh, kw, sh, sw,
                         pad[0], pad[1],
                         activation=_act(cfgd.get("activation")),
                         use_bias=cfgd.get("use_bias", True),
                         name=layer.name)
    if ltype in ("MaxPooling2D", "AveragePooling2D"):
        kh, kw = cfgd["pool_size"]
        sh, sw = cfgd["strides"] or (kh, kw)
        pad = _same_pad(cfgd.get("padding", "valid"), kh, kw, sh, sw, ltype)
        return ff.pool2d(ins[0], kh, kw, sh, sw, pad[0], pad[1],
                         pool_type="max" if ltype.startswith("Max")
                         else "avg", name=layer.name)
    if ltype == "Flatten":
        return ff.flat(ins[0], name=layer.name)
    if ltype == "Dropout":
        return ff.dropout(ins[0], cfgd["rate"], name=layer.name)
    if ltype == "BatchNormalization":
        return ff.batch_norm(ins[0], relu=False, name=layer.name)
    if ltype == "Activation":
        return _apply_act(ff, cfgd["activation"], ins[0], layer.name)
    if ltype == "Concatenate":
        return ff.concat(ins, axis=cfgd.get("axis", -1), name=layer.name)
    if ltype == "Add":
        t = ff.add(ins[0], ins[1], name=layer.name)
        for j, extra in enumerate(ins[2:]):  # tf.keras Add takes N inputs
            t = ff.add(t, extra, name=f"{layer.name}_add{j + 2}")
        return t
    if ltype == "Embedding":
        return ff.embedding(ins[0], cfgd["input_dim"], cfgd["output_dim"],
                            name=layer.name)
    raise NotImplementedError(f"keras_exp: unsupported layer {ltype}")


def _same_pad(padding, kh, kw, sh, sw, ltype):
    """Symmetric padding for TF 'same' — exact only for stride-1 odd
    kernels; TF pads asymmetrically otherwise, so fail loudly rather
    than silently shift the windows of an imported trained model."""
    if padding != "same":
        return (0, 0)
    if (sh, sw) != (1, 1) or kh % 2 == 0 or kw % 2 == 0:
        raise NotImplementedError(
            f"keras_exp: {ltype} padding='same' with strides {(sh, sw)} "
            f"kernel {(kh, kw)} needs TF's asymmetric padding, which "
            "symmetric conv padding cannot represent exactly")
    return (kh // 2, kw // 2)


def _act(name):
    if name in (None, "linear"):
        return None
    if name in ("relu", "sigmoid", "tanh", "elu", "gelu"):
        return name
    # softmax is handled by the Dense caller; anything else fails loudly
    raise NotImplementedError(f"keras_exp: activation {name!r}")


def _apply_act(ff, name, t, lname):
    if name == "softmax":
        return ff.softmax(t, name=lname)
    fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
          "elu": ff.elu, "gelu": ff.gelu}.get(name)
    if fn is None:
        raise NotImplementedError(f"keras_exp: activation {name}")
    return fn(t, name=lname)
