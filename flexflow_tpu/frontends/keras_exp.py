"""Experimental frontend: import a REAL tf.keras model.

Reference: python/flexflow/keras_exp/models/model.py:36-424 — walks a
genuine tf.keras model object (rather than this package's Keras-clone
layer classes) and replays it onto the framework's builder API.

The importer never needs the ``tensorflow`` module itself: every access
goes through the *model object's* own protocol (``.inputs``,
``.layers``, ``layer.get_config()``, ``layer.get_weights()``), so any
object that duck-types tf.keras works — the handler table is exercised
both deps-free through stubs and, when TF is importable (`HAS_TF`),
against real tf.keras models (tests/test_frontends.py). Keras 2 and
Keras 3 symbolic tensors are both supported (`_tref`).

Weight import is an explicit per-layer-type mapping (NOT shape
matching): tf Conv2D kernels are HWIO and are transposed to this
framework's OIHW (ops/conv.py weight_specs); Dense kernels are (in,out)
on both sides; BatchNormalization's [gamma, beta, moving_mean,
moving_variance] map positionally to scale/bias params and
running_mean/running_var *state*. Any tf array that fails to map
raises — same fail-loudly policy as _same_pad/_act.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import tensorflow as _tf  # noqa: F401
    HAS_TF = True
except Exception:  # pragma: no cover - image ships without TF
    _tf = None
    HAS_TF = False


def _tref(t):
    """Hashable key for a tf/keras symbolic tensor: Keras 2 tensors need
    .ref() (not hashable themselves); Keras 3 KerasTensors have no
    .ref() and are identity-keyed."""
    ref = getattr(t, "ref", None)
    return ref() if callable(ref) else id(t)


def from_tf_keras(tf_model, config=None, batch_size: Optional[int] = None,
                  mesh=None, strategy=None):
    """Replay a tf.keras Model (or duck-typed equivalent) onto an
    FFModel; returns the FFModel.

    Layer coverage follows the reference keras_exp handler set; raises
    NotImplementedError on anything else so failures are explicit.
    """
    from ..config import FFConfig
    from ..model import FFModel

    cfg = config or FFConfig()
    bs = batch_size or cfg.batch_size
    ff = FFModel(cfg, mesh=mesh, strategy=strategy)

    values = {}  # tf tensor ref -> framework Tensor

    for inp in tf_model.inputs:
        shape = tuple(int(d) for d in inp.shape[1:])
        values[_tref(inp)] = ff.create_tensor(
            (bs,) + shape, name=inp.name.split(":")[0])

    _replay_layers(ff, tf_model, values)

    # stage trained weights; FFModel.compile applies them after
    # init_state (state does not exist yet at this point)
    ops_by_name = {op.name: op for op in ff.ops}
    for layer in _leaf_layers(tf_model):
        w = layer.get_weights()
        if not w:
            continue
        op = ops_by_name.get(layer.name)
        if op is None:
            raise ValueError(
                f"keras_exp: layer {layer.name!r} has weights but no "
                f"emitted op of that name — import bug")
        params, states = _map_layer_weights(type(layer).__name__, layer, w, op)
        if params:
            ff.imported_weights[layer.name] = params
        if states:
            ff.imported_states[layer.name] = states
    return ff


def _map_layer_weights(ltype, layer, w, op):
    """Explicit tf->framework weight mapping per layer type. Returns
    (params, states) dicts; raises on any array that cannot map."""
    specs = op.weight_specs()
    params, states = {}, {}

    def take(name, arr, transpose=None):
        if transpose is not None:
            arr = np.transpose(arr, transpose)
        spec = specs.get(name)
        if spec is None or tuple(spec.shape) != tuple(np.shape(arr)):
            raise ValueError(
                f"keras_exp: {layer.name} ({ltype}) weight {name!r} "
                f"shape {np.shape(arr)} does not match framework spec "
                f"{tuple(spec.shape) if spec else None}")
        params[name] = np.asarray(arr)

    if ltype == "Dense":
        # tf kernel (in, out) == framework Linear kernel (in, out)
        take("kernel", w[0])
        if len(w) > 1:
            take("bias", w[1])
    elif ltype == "Conv2D":
        # tf HWIO -> framework OIHW (ops/conv.py weight_specs)
        take("kernel", w[0], transpose=(3, 2, 0, 1))
        if len(w) > 1:
            take("bias", w[1])
    elif ltype == "Embedding":
        # tf embeddings (vocab, dim) == framework kernel (vocab, dim)
        take("kernel", w[0])
    elif ltype == "LayerNormalization":
        cfgd = layer.get_config()
        if not (cfgd.get("scale", True) and cfgd.get("center", True)):
            # scale=False would positionally map beta into gamma —
            # silent numeric divergence, same guard as BN below
            raise NotImplementedError(
                "keras_exp: LayerNormalization with scale=False or "
                "center=False changes get_weights() order")
        # tf [gamma, beta] == framework [scale, bias]
        take("scale", w[0])
        if len(w) > 1:
            take("bias", w[1])
    elif ltype == "BatchNormalization":
        cfgd = layer.get_config()
        if not (cfgd.get("scale", True) and cfgd.get("center", True)):
            raise NotImplementedError(
                "keras_exp: BatchNormalization with scale=False or "
                "center=False changes get_weights() order")
        if len(w) != 4:
            raise ValueError(
                f"keras_exp: BatchNormalization {layer.name} expected 4 "
                f"arrays [gamma, beta, moving_mean, moving_variance], "
                f"got {len(w)}")
        gamma, beta, mmean, mvar = w
        take("scale", gamma)
        take("bias", beta)
        sspecs = op.state_specs()
        for name, arr in (("running_mean", mmean), ("running_var", mvar)):
            if tuple(sspecs[name].shape) != tuple(np.shape(arr)):
                raise ValueError(
                    f"keras_exp: BN {layer.name} state {name} shape "
                    f"{np.shape(arr)} != {tuple(sspecs[name].shape)}")
            states[name] = np.asarray(arr)
    else:
        raise NotImplementedError(
            f"keras_exp: layer {ltype} ({layer.name}) has weights but no "
            f"weight-import mapping")
    return params, states


def _replay_layers(ff, tf_model, values):
    """Walk a Model's layer graph, emitting framework ops. A nested
    Model used as a layer (reference keras_exp func_cifar10_cnn_nested
    pattern) is inlined: its symbolic inputs are bound to the caller's
    incoming tensors and its internal graph replays into the same
    FFModel."""
    for layer in tf_model.layers:
        ltype = type(layer).__name__
        if ltype == "InputLayer":
            continue
        if hasattr(layer, "layers") and getattr(layer, "inputs", None):
            # nested Model as a layer: `layer.inputs/outputs` are its
            # OWN construction graph; the call-site tensors live on the
            # inbound node. Bind call-site -> internal inputs, replay
            # the internal graph, then bind internal outputs back to
            # the call-site tensors downstream layers reference.
            if len(getattr(layer, "_inbound_nodes", [])) > 1:
                raise NotImplementedError(
                    f"keras_exp: nested Model {layer.name!r} is called "
                    f"at {len(layer._inbound_nodes)} sites; shared "
                    f"submodels are unsupported (weight-tying across "
                    f"call sites has no op-per-layer mapping) — call "
                    f"each submodel once or flatten the model")
            node = layer._inbound_nodes[-1]
            outer_ins = node.input_tensors
            if not isinstance(outer_ins, (list, tuple)):
                outer_ins = [outer_ins]
            for inner, outer in zip(layer.inputs, outer_ins):
                values[_tref(inner)] = values[_tref(outer)]
            _replay_layers(ff, layer, values)
            outer_outs = node.output_tensors
            if not isinstance(outer_outs, (list, tuple)):
                outer_outs = [outer_outs]
            for outer, inner in zip(outer_outs, layer.outputs):
                values[_tref(outer)] = values[_tref(inner)]
            continue
        ins = [values[_tref(t)] for t in _flat_inputs(layer)]
        # Keras guarantees unique layer names only PER model; inlining
        # a nested Model can bring an inner 'fc' next to an outer 'fc'.
        # Ops/params/imported_weights are all name-keyed — a silent
        # duplicate would make one layer read the other's weights.
        if any(op.name == layer.name for op in ff.ops):
            raise NotImplementedError(
                f"keras_exp: duplicate layer name {layer.name!r} after "
                f"nested-Model inlining; give inner and outer layers "
                f"distinct names")
        out = _emit_layer(ff, layer, ltype, ins)
        for t in _flat_outputs(layer):
            values[_tref(t)] = out


def _leaf_layers(tf_model):
    """Layers with weights of their own, nested Models flattened."""
    for layer in tf_model.layers:
        if hasattr(layer, "layers"):
            yield from _leaf_layers(layer)
        else:
            yield layer


def _flat_inputs(layer):
    x = layer.input
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _flat_outputs(layer):
    x = layer.output
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _emit_layer(ff, layer, ltype, ins):
    cfgd = layer.get_config()
    # this framework's image layout is NCHW (reference examples parity);
    # real tf.keras defaults to channels_last — fail loudly rather than
    # silently treating H as the channel dim. (Stub models without the
    # key are assumed channels_first.)
    if (ltype in ("Conv2D", "MaxPooling2D", "AveragePooling2D")
            and cfgd.get("data_format", "channels_first")
            == "channels_last"):
        raise NotImplementedError(
            f"keras_exp: {ltype} ({layer.name}) uses channels_last; "
            f"build the tf model with data_format='channels_first' "
            f"(weights import fine either way — kernels are HWIO)")
    if ltype == "Dense":
        act = cfgd.get("activation")
        t = ff.dense(ins[0], cfgd["units"],
                     activation=None if act == "softmax" else _act(act),
                     use_bias=cfgd.get("use_bias", True), name=layer.name)
        if act == "softmax":
            t = ff.softmax(t, name=f"{layer.name}_softmax")
        return t
    if ltype == "Conv2D":
        kh, kw = cfgd["kernel_size"]
        sh, sw = cfgd["strides"]
        pad = _same_pad(cfgd["padding"], kh, kw, sh, sw, ltype)
        return ff.conv2d(ins[0], cfgd["filters"], kh, kw, sh, sw,
                         pad[0], pad[1],
                         activation=_act(cfgd.get("activation")),
                         use_bias=cfgd.get("use_bias", True),
                         name=layer.name)
    if ltype in ("MaxPooling2D", "AveragePooling2D"):
        kh, kw = cfgd["pool_size"]
        sh, sw = cfgd["strides"] or (kh, kw)
        pad = _same_pad(cfgd.get("padding", "valid"), kh, kw, sh, sw, ltype)
        return ff.pool2d(ins[0], kh, kw, sh, sw, pad[0], pad[1],
                         pool_type="max" if ltype.startswith("Max")
                         else "avg", name=layer.name)
    if ltype == "Flatten":
        return ff.flat(ins[0], name=layer.name)
    if ltype == "Dropout":
        return ff.dropout(ins[0], cfgd["rate"], name=layer.name)
    if ltype == "BatchNormalization":
        return ff.batch_norm(ins[0], relu=False, name=layer.name)
    if ltype == "Activation":
        return _apply_act(ff, cfgd["activation"], ins[0], layer.name)
    if ltype == "Concatenate":
        return ff.concat(ins, axis=cfgd.get("axis", -1), name=layer.name)
    if ltype == "Add":
        t = ff.add(ins[0], ins[1], name=layer.name)
        for j, extra in enumerate(ins[2:]):  # tf.keras Add takes N inputs
            t = ff.add(t, extra, name=f"{layer.name}_add{j + 2}")
        return t
    if ltype == "Embedding":
        if cfgd.get("mask_zero"):
            # tf propagates the mask (e.g. masked-mean pooling); a
            # plain lookup would silently pool over padding
            raise NotImplementedError(
                "keras_exp: Embedding(mask_zero=True) masking is not "
                "propagated")
        return ff.embedding(ins[0], cfgd["input_dim"], cfgd["output_dim"],
                            aggr="none", name=layer.name)
    if ltype == "GlobalAveragePooling1D":
        if cfgd.get("keepdims") or \
                cfgd.get("data_format", "channels_last") != "channels_last":
            raise NotImplementedError(
                f"keras_exp: GlobalAveragePooling1D keepdims/"
                f"channels_first configs are unsupported "
                f"({ {k: cfgd.get(k) for k in ('keepdims', 'data_format')} })")
        return ff.reduce_mean(ins[0], axis=1, name=layer.name)
    if ltype == "LayerNormalization":
        axis = cfgd.get("axis", -1)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        if list(axes) not in ([-1], [len(layer.input.shape) - 1]):
            raise NotImplementedError(
                f"keras_exp: LayerNormalization axis={axis}; only "
                f"last-dim normalization is supported")
        return ff.layer_norm(ins[0], eps=cfgd.get("epsilon", 1e-3),
                             name=layer.name)
    raise NotImplementedError(f"keras_exp: unsupported layer {ltype}")


def _same_pad(padding, kh, kw, sh, sw, ltype):
    """Symmetric padding for TF 'same' — exact only for stride-1 odd
    kernels; TF pads asymmetrically otherwise, so fail loudly rather
    than silently shift the windows of an imported trained model."""
    if padding != "same":
        return (0, 0)
    if (sh, sw) != (1, 1) or kh % 2 == 0 or kw % 2 == 0:
        raise NotImplementedError(
            f"keras_exp: {ltype} padding='same' with strides {(sh, sw)} "
            f"kernel {(kh, kw)} needs TF's asymmetric padding, which "
            "symmetric conv padding cannot represent exactly")
    return (kh // 2, kw // 2)


def _act(name):
    if name in (None, "linear"):
        return None
    if name in ("relu", "sigmoid", "tanh", "elu", "gelu"):
        return name
    # softmax is handled by the Dense caller; anything else fails loudly
    raise NotImplementedError(f"keras_exp: activation {name!r}")


def _apply_act(ff, name, t, lname):
    if name == "softmax":
        return ff.softmax(t, name=lname)
    fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
          "elu": ff.elu, "gelu": ff.gelu}.get(name)
    if fn is None:
        raise NotImplementedError(f"keras_exp: activation {name}")
    return fn(t, name=lname)
