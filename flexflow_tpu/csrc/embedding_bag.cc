// Native embedding-bag: host-side gather-reduce over an embedding table.
//
// The reference ships a hand-vectorized AVX2 CPU embedding-bag
// (src/ops/embedding_avx2.cc, fbgemm-style) so DLRM strategies can place
// embedding lookups on CPUs next to the data source.  On TPU the *model*
// embedding runs on-chip (ops/embedding.py), so the native bag's role
// moves into the data pipeline: pre-reducing multi-hot categorical
// features on the host before the batch ships to the device, which
// shrinks H2D traffic from (B, L) indices x on-chip gather to a dense
// (B, D) row per feature.  Vectorization is left to the compiler
// (-O3 auto-vectorizes the inner dim-D loops; AVX2 intrinsics would pin
// the ISA for no measurable gain at typical D of 16-128).

#include "flexflow_tpu_c.h"

#include <cstdint>

extern "C" void ffdl_embedding_bag(const float *table, int64_t num_entries,
                                   int32_t dim, const int64_t *indices,
                                   int64_t batch, int32_t bag_size,
                                   int32_t mode /* 0=sum, 1=mean */,
                                   float *out) {
  for (int64_t b = 0; b < batch; ++b) {
    float *dst = out + b * dim;
    for (int32_t d = 0; d < dim; ++d) dst[d] = 0.0f;
    int32_t valid = 0;
    for (int32_t j = 0; j < bag_size; ++j) {
      int64_t idx = indices[b * bag_size + j];
      if (idx < 0 || idx >= num_entries) continue;  // padding slot
      ++valid;
      const float *src = table + idx * dim;
      for (int32_t d = 0; d < dim; ++d) dst[d] += src[d];
    }
    if (mode == 1 && valid > 1) {
      float inv = 1.0f / static_cast<float>(valid);
      for (int32_t d = 0; d < dim; ++d) dst[d] *= inv;
    }
  }
}
