// Native event-driven task-graph simulator.
//
// The hot loop of strategy search: the MCMC walk calls simulate()
// thousands of times per search (reference: Simulator::simulate_runtime,
// src/runtime/simulator.cc:330-629, driven from FFModel::optimize).
// Semantics match flexflow_tpu/search/simulator.py TaskGraph.simulate
// exactly: min-heap keyed on (ready_time, insertion counter), each task
// serializing on its resource's free time.

#include "sim_core.h"
#include "flexflow_tpu_c.h"

#include <algorithm>
#include <queue>
#include <tuple>

namespace fftpu {

namespace {
struct HeapEntry {
  double ready;
  int64_t counter;
  int32_t task;
  bool operator>(const HeapEntry &o) const {
    if (ready != o.ready) return ready > o.ready;
    return counter > o.counter;
  }
};
}  // namespace

double simulate(const std::vector<Task> &tasks,
                const std::vector<int32_t> &dep_indices) {
  const int32_t n = static_cast<int32_t>(tasks.size());
  std::vector<int32_t> unresolved(n, 0);
  std::vector<double> ready_time(n, 0.0);

  // children CSR (built per call; graphs are small — O(5 * n_ops))
  std::vector<int32_t> child_count(n, 0);
  for (int32_t i = 0; i < n; ++i) {
    for (int32_t d = 0; d < tasks[i].n_deps; ++d) {
      int32_t dep = dep_indices[tasks[i].first_dep + d];
      ++child_count[dep];
      ++unresolved[i];
    }
  }
  std::vector<int32_t> child_ptr(n + 1, 0);
  for (int32_t i = 0; i < n; ++i) child_ptr[i + 1] = child_ptr[i] + child_count[i];
  std::vector<int32_t> children(child_ptr[n]);
  {
    std::vector<int32_t> cur(child_ptr.begin(), child_ptr.end() - 1);
    for (int32_t i = 0; i < n; ++i)
      for (int32_t d = 0; d < tasks[i].n_deps; ++d) {
        int32_t dep = dep_indices[tasks[i].first_dep + d];
        children[cur[dep]++] = i;
      }
  }

  int32_t max_res = 0;
  for (const auto &t : tasks) max_res = std::max(max_res, t.resource);
  std::vector<double> free_at(max_res + 1, 0.0);

  std::priority_queue<HeapEntry, std::vector<HeapEntry>, std::greater<HeapEntry>> q;
  int64_t counter = 0;
  for (int32_t i = 0; i < n; ++i)
    if (unresolved[i] == 0) q.push({0.0, counter++, i});

  double makespan = 0.0;
  int32_t done = 0;
  while (!q.empty()) {
    HeapEntry e = q.top();
    q.pop();
    const Task &t = tasks[e.task];
    double start = std::max(e.ready, free_at[t.resource]);
    double finish = start + t.duration;
    free_at[t.resource] = finish;
    makespan = std::max(makespan, finish);
    ++done;
    for (int32_t c = child_ptr[e.task]; c < child_ptr[e.task + 1]; ++c) {
      int32_t ci = children[c];
      ready_time[ci] = std::max(ready_time[ci], finish);
      if (--unresolved[ci] == 0) q.push({ready_time[ci], counter++, ci});
    }
  }
  // done < n means a dependency cycle; report -1 so callers can assert.
  return done == n ? makespan : -1.0;
}

double simulate_multi(const std::vector<MTask> &tasks,
                      const std::vector<int32_t> &res_indices,
                      const std::vector<int32_t> &dep_indices) {
  const int32_t n = static_cast<int32_t>(tasks.size());
  std::vector<int32_t> unresolved(n, 0);
  std::vector<double> ready_time(n, 0.0);

  std::vector<int32_t> child_count(n, 0);
  for (int32_t i = 0; i < n; ++i) {
    for (int32_t d = 0; d < tasks[i].n_deps; ++d) {
      int32_t dep = dep_indices[tasks[i].first_dep + d];
      ++child_count[dep];
      ++unresolved[i];
    }
  }
  std::vector<int32_t> child_ptr(n + 1, 0);
  for (int32_t i = 0; i < n; ++i)
    child_ptr[i + 1] = child_ptr[i] + child_count[i];
  std::vector<int32_t> children(child_ptr[n]);
  {
    std::vector<int32_t> cur(child_ptr.begin(), child_ptr.end() - 1);
    for (int32_t i = 0; i < n; ++i)
      for (int32_t d = 0; d < tasks[i].n_deps; ++d) {
        int32_t dep = dep_indices[tasks[i].first_dep + d];
        children[cur[dep]++] = i;
      }
  }

  int32_t max_res = 0;
  for (const auto &t : tasks)
    for (int32_t r = 0; r < t.n_res; ++r)
      max_res = std::max(max_res, res_indices[t.first_res + r]);
  std::vector<double> free_at(max_res + 1, 0.0);

  std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                      std::greater<HeapEntry>> q;
  int64_t counter = 0;
  for (int32_t i = 0; i < n; ++i)
    if (unresolved[i] == 0) q.push({0.0, counter++, i});

  double makespan = 0.0;
  int32_t done = 0;
  while (!q.empty()) {
    HeapEntry e = q.top();
    q.pop();
    const MTask &t = tasks[e.task];
    double start = e.ready;
    for (int32_t r = 0; r < t.n_res; ++r)
      start = std::max(start, free_at[res_indices[t.first_res + r]]);
    double finish = start + t.duration;
    for (int32_t r = 0; r < t.n_res; ++r)
      free_at[res_indices[t.first_res + r]] = finish;
    makespan = std::max(makespan, finish);
    ++done;
    for (int32_t c = child_ptr[e.task]; c < child_ptr[e.task + 1]; ++c) {
      int32_t ci = children[c];
      ready_time[ci] = std::max(ready_time[ci], finish);
      if (--unresolved[ci] == 0) q.push({ready_time[ci], counter++, ci});
    }
  }
  return done == n ? makespan : -1.0;
}

}  // namespace fftpu

extern "C" double ffsim_simulate(int32_t n_tasks, const double *durations,
                                 const int32_t *resources,
                                 const int32_t *dep_indptr,
                                 const int32_t *dep_indices) {
  std::vector<fftpu::Task> tasks(n_tasks);
  for (int32_t i = 0; i < n_tasks; ++i) {
    tasks[i].duration = durations[i];
    tasks[i].resource = resources[i];
    tasks[i].first_dep = dep_indptr[i];
    tasks[i].n_deps = dep_indptr[i + 1] - dep_indptr[i];
  }
  std::vector<int32_t> deps(dep_indices, dep_indices + dep_indptr[n_tasks]);
  return fftpu::simulate(tasks, deps);
}

extern "C" const char *flexflow_tpu_native_version(void) {
  return "flexflow-tpu-native 0.1";
}
