// Native prefetching batch gatherer.
//
// The reference SingleDataLoader keeps the whole dataset in zero-copy
// host memory and copies per-batch slices to device regions on demand
// (python/flexflow_dataloader.cc:576-740).  Here the expensive host-side
// step is the gather of shuffled rows into a contiguous batch buffer;
// this runs on a background thread, double-buffered, so the gather for
// batch i+1 overlaps JAX dispatch + H2D transfer of batch i.

#include "flexflow_tpu_c.h"

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Loader {
  // dataset
  std::vector<const char *> data;
  std::vector<int64_t> row_bytes;
  int64_t n_samples = 0;
  int32_t batch_size = 0;
  bool drop_last = true;

  // epoch state
  std::vector<int64_t> order;
  int32_t num_batches = 0;

  // double buffers: buf[slot][array]
  std::vector<std::vector<char>> buf[2];
  int32_t buf_rows[2] = {0, 0};
  int32_t buf_batch[2] = {-1, -1};  // which batch index each slot holds
  bool buf_ready[2] = {false, false};

  // producer thread
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_produced, cv_consumed;
  int32_t produce_next = 0;  // next batch index the worker will gather
  int32_t consume_next = 0;  // next batch index the caller will take
  std::atomic<bool> stop{false};
  bool epoch_active = false;
  bool gathering = false;  // worker is copying outside the lock

  void gather(int32_t batch_idx, int32_t slot) {
    int64_t start = static_cast<int64_t>(batch_idx) * batch_size;
    int64_t end = std::min<int64_t>(start + batch_size, n_samples);
    int32_t rows = static_cast<int32_t>(end - start);
    for (size_t k = 0; k < data.size(); ++k) {
      char *dst = buf[slot][k].data();
      const char *src = data[k];
      int64_t rb = row_bytes[k];
      for (int64_t r = 0; r < rows; ++r)
        std::memcpy(dst + r * rb, src + order[start + r] * rb, rb);
    }
    buf_rows[slot] = rows;
    buf_batch[slot] = batch_idx;
  }

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    while (!stop.load()) {
      if (!epoch_active || produce_next >= num_batches ||
          buf_ready[produce_next % 2]) {
        cv_consumed.wait(lk, [&] {
          return stop.load() ||
                 (epoch_active && produce_next < num_batches &&
                  !buf_ready[produce_next % 2]);
        });
        continue;
      }
      int32_t b = produce_next;
      int32_t slot = b % 2;
      gathering = true;
      lk.unlock();
      gather(b, slot);  // heavy work outside the lock
      lk.lock();
      gathering = false;
      if (!epoch_active || produce_next != b) {
        cv_produced.notify_all();  // epoch restarted mid-gather; discard
        continue;
      }
      buf_ready[slot] = true;
      ++produce_next;
      cv_produced.notify_all();
    }
  }
};

}  // namespace

extern "C" ffdl_handle_t ffdl_create(int32_t n_arrays,
                                     const void *const *data_ptrs,
                                     const int64_t *row_bytes,
                                     int64_t n_samples, int32_t batch_size,
                                     int32_t drop_last) {
  auto *l = new Loader();
  for (int32_t k = 0; k < n_arrays; ++k) {
    l->data.push_back(static_cast<const char *>(data_ptrs[k]));
    l->row_bytes.push_back(row_bytes[k]);
  }
  l->n_samples = n_samples;
  l->batch_size = batch_size;
  l->drop_last = drop_last != 0;
  for (int s = 0; s < 2; ++s) {
    l->buf[s].resize(n_arrays);
    for (int32_t k = 0; k < n_arrays; ++k)
      l->buf[s][k].resize(static_cast<size_t>(batch_size) * row_bytes[k]);
  }
  l->worker = std::thread([l] { l->run(); });
  return l;
}

extern "C" void ffdl_start_epoch(ffdl_handle_t h, const int64_t *order) {
  auto *l = static_cast<Loader *>(h);
  std::unique_lock<std::mutex> lk(l->mu);
  // park the worker before touching `order` (it reads order outside the
  // lock while gathering)
  l->epoch_active = false;
  l->cv_produced.wait(lk, [&] { return !l->gathering; });
  l->order.assign(order, order + l->n_samples);
  int64_t nb = l->n_samples / l->batch_size;
  if (!l->drop_last && l->n_samples % l->batch_size) ++nb;
  l->num_batches = static_cast<int32_t>(nb);
  l->produce_next = 0;
  l->consume_next = 0;
  l->buf_ready[0] = l->buf_ready[1] = false;
  l->buf_batch[0] = l->buf_batch[1] = -1;
  l->epoch_active = true;
  l->cv_consumed.notify_all();
}

extern "C" int32_t ffdl_num_batches(ffdl_handle_t h) {
  auto *l = static_cast<Loader *>(h);
  std::unique_lock<std::mutex> lk(l->mu);
  return l->num_batches;
}

extern "C" int32_t ffdl_next_batch(ffdl_handle_t h, void **out_ptrs,
                                   int32_t *out_rows) {
  auto *l = static_cast<Loader *>(h);
  std::unique_lock<std::mutex> lk(l->mu);
  if (!l->epoch_active || l->consume_next >= l->num_batches) return -1;
  int32_t b = l->consume_next;
  int32_t slot = b % 2;
  // release the previous batch's slot so the worker can refill it
  int32_t prev_slot = 1 - slot;
  if (l->buf_batch[prev_slot] >= 0 && l->buf_batch[prev_slot] < b) {
    l->buf_ready[prev_slot] = false;
    l->cv_consumed.notify_all();
  }
  l->cv_produced.wait(lk, [&] { return l->buf_ready[slot] &&
                                       l->buf_batch[slot] == b; });
  for (size_t k = 0; k < l->data.size(); ++k)
    out_ptrs[k] = l->buf[slot][k].data();
  *out_rows = l->buf_rows[slot];
  ++l->consume_next;
  return b;
}

extern "C" void ffdl_destroy(ffdl_handle_t h) {
  auto *l = static_cast<Loader *>(h);
  {
    std::unique_lock<std::mutex> lk(l->mu);
    l->stop.store(true);
    l->cv_consumed.notify_all();
  }
  l->worker.join();
  delete l;
}
