// Native MCMC strategy-search annealing loop.
//
// The analog of FFModel::optimize (reference src/runtime/model.cc:1905-1968):
// simulated annealing over per-op strategy candidates with `rewrite` and
// `propagate` moves, accepting uphill moves with prob exp(-delta/(alpha*cur)),
// resetting to the best strategy every budget/100 iterations.  Candidate
// costs are precomputed by the Python cost model (the TPU stand-in for
// Op::measure_operator_cost); this file owns the hot loop: per-iteration
// task-graph construction + event simulation, matching
// flexflow_tpu/search/simulator.py Simulator._simulate_raw exactly —
// including device-explicit placements (per-device resources so disjoint
// placements run concurrently) and pipeline candidates expanded into the
// real (microbatch, stage) GPipe schedule.  Fusion folding remains
// Python-only: fused searches route to the Python engine.

#include "sim_core.h"
#include "flexflow_tpu_c.h"

#include <algorithm>
#include <cmath>
#include <random>
#include <vector>

namespace {

using fftpu::MTask;

// Fixed resource ids; device resources are 2..2+n_dev-1; per-op stage
// and join resources are allocated after them during construction.
constexpr int32_t kCompute = 0;
constexpr int32_t kComm = 1;

// Edge lists grouped per op, preserving the caller's edge order (which
// is the Python simulator's iteration order over op.inputs).
struct Graph {
  int32_t n_ops = 0;
  int32_t n_dev = 0;
  std::vector<int32_t> in_ptr, in_idx;    // producers of op (by dst)
  std::vector<int32_t> out_ptr, out_idx;  // consumers of op (by src)
};

Graph build_graph(int32_t n_ops, int32_t n_dev, int32_t n_edges,
                  const int32_t *edge_src, const int32_t *edge_dst) {
  Graph g;
  g.n_ops = n_ops;
  g.n_dev = n_dev;
  g.in_ptr.assign(n_ops + 1, 0);
  g.out_ptr.assign(n_ops + 1, 0);
  for (int32_t e = 0; e < n_edges; ++e) {
    ++g.in_ptr[edge_dst[e] + 1];
    ++g.out_ptr[edge_src[e] + 1];
  }
  for (int32_t i = 0; i < n_ops; ++i) {
    g.in_ptr[i + 1] += g.in_ptr[i];
    g.out_ptr[i + 1] += g.out_ptr[i];
  }
  g.in_idx.resize(n_edges);
  g.out_idx.resize(n_edges);
  std::vector<int32_t> ic(g.in_ptr.begin(), g.in_ptr.end() - 1);
  std::vector<int32_t> oc(g.out_ptr.begin(), g.out_ptr.end() - 1);
  for (int32_t e = 0; e < n_edges; ++e) {
    g.in_idx[ic[edge_dst[e]]++] = edge_src[e];
    g.out_idx[oc[edge_src[e]]++] = edge_dst[e];
  }
  return g;
}

// Per-(op, candidate) costs, flattened.  place_* carries the explicit
// device list of placed candidates (OpStrategy.device_ids); pipe_*
// carries the PipelineCost fields of layer->pipe candidates.
struct Costs {
  const int32_t *cand_offsets;
  const double *fwd, *bwd, *fwd_comm, *bwd_comm, *sync, *mem;
  const int32_t *place_off;   // into place_ids, len total_cands+1
  const int32_t *place_ids;
  const int32_t *pipe_stages; // 0 = not pipelined
  const int32_t *pipe_mb;
  const double *pipe_fwd_stage, *pipe_bwd_stage, *pipe_hop;
  int32_t at(int32_t op, int32_t cand) const { return cand_offsets[op] + cand; }
};

// Reusable scratch so the annealing loop does no allocation churn.
struct SimScratch {
  std::vector<MTask> tasks;
  std::vector<int32_t> deps;
  std::vector<int32_t> res;
  std::vector<int32_t> fwd_task, bwd_task;
  std::vector<int32_t> sync_tasks;
  std::vector<int32_t> tmp_deps;
  // per-(op) forward stage-task ids for expanded pipelines, row-major
  // (m * S + k); indexed via pipe_rows_off[op]
  std::vector<int32_t> pipe_rows;
  std::vector<int32_t> pipe_rows_off;
  int32_t next_res = 0;

  void reset(int32_t n_ops, int32_t n_dev) {
    tasks.clear();
    deps.clear();
    res.clear();
    sync_tasks.clear();
    pipe_rows.clear();
    pipe_rows_off.assign(n_ops, -1);
    fwd_task.assign(n_ops, -1);
    bwd_task.assign(n_ops, -1);
    next_res = 2 + n_dev;
  }

  int32_t add(double duration, int32_t resource,
              const std::vector<int32_t> &dep_list) {
    MTask t;
    t.duration = duration;
    t.first_res = static_cast<int32_t>(res.size());
    t.n_res = 1;
    res.push_back(resource);
    t.first_dep = static_cast<int32_t>(deps.size());
    t.n_deps = static_cast<int32_t>(dep_list.size());
    deps.insert(deps.end(), dep_list.begin(), dep_list.end());
    tasks.push_back(t);
    return static_cast<int32_t>(tasks.size()) - 1;
  }

  int32_t add_multi(double duration, const std::vector<int32_t> &resources,
                    const std::vector<int32_t> &dep_list) {
    MTask t;
    t.duration = duration;
    t.first_res = static_cast<int32_t>(res.size());
    t.n_res = static_cast<int32_t>(resources.size());
    res.insert(res.end(), resources.begin(), resources.end());
    t.first_dep = static_cast<int32_t>(deps.size());
    t.n_deps = static_cast<int32_t>(dep_list.size());
    deps.insert(deps.end(), dep_list.begin(), dep_list.end());
    tasks.push_back(t);
    return static_cast<int32_t>(tasks.size()) - 1;
  }
};

// Build the training-step task graph for one candidate assignment and
// event-simulate it.  Mirrors Simulator._simulate_raw task-for-task
// (construction order matters: FIFO tie-breaking keys on insertion).
double simulate_assignment(const Graph &g, const Costs &c,
                           const int32_t *assign, bool overlap,
                           double hbm_capacity, double time_scale,
                           double step_overhead, SimScratch &s) {
  if (g.n_ops == 0) return 0.0;
  s.reset(g.n_ops, g.n_dev);
  double total_mem = 0.0;

  // SPMD ops occupy compute + every device resource once any placed
  // candidate is active (Python res_for)
  bool any_placed = false;
  for (int32_t op = 0; op < g.n_ops; ++op) {
    int32_t k = c.at(op, assign[op]);
    if (c.place_off[k + 1] > c.place_off[k]) any_placed = true;
  }
  std::vector<int32_t> spmd_res{kCompute};
  if (any_placed)
    for (int32_t d = 0; d < g.n_dev; ++d) spmd_res.push_back(2 + d);
  std::vector<int32_t> placed_res;

  auto res_for = [&](int32_t k) -> const std::vector<int32_t> & {
    int32_t p0 = c.place_off[k], p1 = c.place_off[k + 1];
    if (p1 > p0) {
      placed_res.clear();
      for (int32_t p = p0; p < p1; ++p)
        placed_res.push_back(2 + c.place_ids[p]);
      return placed_res;
    }
    return spmd_res;
  };

  // ---- forward chain ----
  for (int32_t op = 0; op < g.n_ops; ++op) {
    int32_t k = c.at(op, assign[op]);
    s.tmp_deps.clear();
    for (int32_t e = g.in_ptr[op]; e < g.in_ptr[op + 1]; ++e)
      s.tmp_deps.push_back(s.fwd_task[g.in_idx[e]]);

    int32_t S = c.pipe_stages[k];
    if (S > 1) {
      // GPipe expansion (Python _expand_pipeline_fwd): stage k of op is
      // its own resource; one hop between stages; zero-duration join
      int32_t M = c.pipe_mb[k];
      double tf = c.pipe_fwd_stage[k], hop = c.pipe_hop[k];
      int32_t stage_base = s.next_res;
      s.next_res += S;
      int32_t join_f = s.next_res++;  // join resources (unique)
      s.pipe_rows_off[op] = static_cast<int32_t>(s.pipe_rows.size());
      std::vector<int32_t> ext = s.tmp_deps;
      std::vector<int32_t> dl;
      for (int32_t m = 0; m < M; ++m) {
        int32_t prev = -1;
        for (int32_t st = 0; st < S; ++st) {
          dl.clear();
          if (st == 0) dl = ext;
          if (prev >= 0) {
            if (hop > 0) {
              dl.push_back(s.add(hop, kComm, {prev}));
            } else {
              dl.push_back(prev);
            }
          }
          prev = s.add(tf, stage_base + st, dl);
          s.pipe_rows.push_back(prev);
        }
      }
      dl.clear();
      for (int32_t m = 0; m < M; ++m)
        dl.push_back(s.pipe_rows[s.pipe_rows_off[op] + m * S + S - 1]);
      s.fwd_task[op] = s.add(0.0, join_f, dl);
    } else {
      if (c.fwd_comm[k] > 0) {
        int32_t comm = s.add(c.fwd_comm[k], kComm, s.tmp_deps);
        s.tmp_deps.push_back(comm);
      }
      s.fwd_task[op] = s.add_multi(c.fwd[k], res_for(k), s.tmp_deps);
    }
    total_mem += c.mem[k];
  }

  // ---- backward chain (reverse graph) ----
  const int32_t last_fwd = s.fwd_task[g.n_ops - 1];
  for (int32_t op = g.n_ops - 1; op >= 0; --op) {
    int32_t k = c.at(op, assign[op]);
    s.tmp_deps.clear();
    for (int32_t e = g.out_ptr[op]; e < g.out_ptr[op + 1]; ++e) {
      int32_t cons = g.out_idx[e];
      if (s.bwd_task[cons] >= 0) s.tmp_deps.push_back(s.bwd_task[cons]);
    }
    if (s.tmp_deps.empty()) s.tmp_deps.push_back(last_fwd);

    int32_t S = c.pipe_stages[k];
    if (S > 1) {
      // Python _expand_pipeline_bwd: stage S-1..0 per microbatch, each
      // tick also depends on that microbatch's forward at the stage
      int32_t M = c.pipe_mb[k];
      double tb = c.pipe_bwd_stage[k], hop = c.pipe_hop[k];
      // stage resources were allocated in the forward pass in op order;
      // recover them from the first fwd stage task of this op
      int32_t row0 = s.pipe_rows_off[op];
      int32_t stage_base = s.res[s.tasks[s.pipe_rows[row0]].first_res];
      int32_t join_b = s.next_res++;
      std::vector<int32_t> ext = s.tmp_deps;
      std::vector<int32_t> dl, exits;
      for (int32_t m = 0; m < M; ++m) {
        int32_t prev = -1;
        for (int32_t st = S - 1; st >= 0; --st) {
          dl.clear();
          if (st == S - 1) dl = ext;
          dl.push_back(s.pipe_rows[row0 + m * S + st]);
          if (prev >= 0) {
            if (hop > 0) {
              dl.push_back(s.add(hop, kComm, {prev}));
            } else {
              dl.push_back(prev);
            }
          }
          prev = s.add(tb, stage_base + st, dl);
        }
        exits.push_back(prev);
      }
      s.bwd_task[op] = s.add(0.0, join_b, exits);
    } else {
      if (c.bwd_comm[k] > 0) {
        int32_t comm = s.add(c.bwd_comm[k], kComm, s.tmp_deps);
        s.tmp_deps.push_back(comm);
      }
      s.bwd_task[op] = s.add_multi(c.bwd[k], res_for(k), s.tmp_deps);
    }
    if (c.sync[k] > 0) {
      s.tmp_deps.clear();
      s.tmp_deps.push_back(s.bwd_task[op]);
      s.sync_tasks.push_back(s.add(c.sync[k], kComm, s.tmp_deps));
    }
  }

  if (!overlap && !s.sync_tasks.empty()) {
    // serialize syncs after all backward work: each sync additionally
    // depends on the first op's bwd, the last one computed (mirrors the
    // Python st.deps.append(last_bwd))
    for (int32_t st : s.sync_tasks) {
      int32_t own_bwd = s.deps[s.tasks[st].first_dep];
      s.tasks[st].first_dep = static_cast<int32_t>(s.deps.size());
      s.tasks[st].n_deps = 2;
      s.deps.push_back(own_bwd);
      s.deps.push_back(s.bwd_task[0]);
    }
  }

  double makespan = fftpu::simulate_multi(s.tasks, s.res, s.deps);
  double over = total_mem - hbm_capacity;
  double penalty = over > 0 ? over * 1e-9 : 0.0;
  return makespan * time_scale + penalty + step_overhead;
}

}  // namespace

extern "C" double ffsearch_simulate_assignment(
    int32_t n_ops, const int32_t *cand_offsets, const double *cost_fwd,
    const double *cost_bwd, const double *cost_fwd_comm,
    const double *cost_bwd_comm, const double *cost_sync,
    const double *cost_mem, const int32_t *place_off,
    const int32_t *place_ids, const int32_t *pipe_stages,
    const int32_t *pipe_mb, const double *pipe_fwd_stage,
    const double *pipe_bwd_stage, const double *pipe_hop, int32_t n_dev,
    int32_t n_edges, const int32_t *edge_src, const int32_t *edge_dst,
    int32_t overlap_backward_sync, double hbm_capacity, double time_scale,
    double step_overhead, const int32_t *assignment) {
  Graph g = build_graph(n_ops, n_dev, n_edges, edge_src, edge_dst);
  Costs c{cand_offsets, cost_fwd,   cost_bwd,      cost_fwd_comm,
          cost_bwd_comm, cost_sync, cost_mem,      place_off,
          place_ids,     pipe_stages, pipe_mb,     pipe_fwd_stage,
          pipe_bwd_stage, pipe_hop};
  SimScratch s;
  return simulate_assignment(g, c, assignment, overlap_backward_sync != 0,
                             hbm_capacity, time_scale, step_overhead, s);
}

extern "C" double ffsearch_mcmc(
    int32_t n_ops, const int32_t *n_cands, const int32_t *cand_offsets,
    const double *cost_fwd, const double *cost_bwd,
    const double *cost_fwd_comm, const double *cost_bwd_comm,
    const double *cost_sync, const double *cost_mem,
    const int32_t *place_off, const int32_t *place_ids,
    const int32_t *pipe_stages, const int32_t *pipe_mb,
    const double *pipe_fwd_stage, const double *pipe_bwd_stage,
    const double *pipe_hop, int32_t n_dev, int32_t n_edges,
    const int32_t *edge_src, const int32_t *edge_dst,
    const int32_t *prop_offsets, const int32_t *prop_match, int32_t budget,
    double alpha, uint64_t seed, int32_t enable_propagation,
    int32_t overlap_backward_sync, double hbm_capacity, double time_scale,
    double step_overhead, const int32_t *init_cand, int32_t *best_out) {
  Graph g = build_graph(n_ops, n_dev, n_edges, edge_src, edge_dst);
  Costs c{cand_offsets, cost_fwd,   cost_bwd,      cost_fwd_comm,
          cost_bwd_comm, cost_sync, cost_mem,      place_off,
          place_ids,     pipe_stages, pipe_mb,     pipe_fwd_stage,
          pipe_bwd_stage, pipe_hop};
  SimScratch s;
  const bool overlap = overlap_backward_sync != 0;

  std::vector<int32_t> current(init_cand, init_cand + n_ops);
  std::vector<int32_t> best = current;
  std::vector<int32_t> searchable;
  for (int32_t i = 0; i < n_ops; ++i)
    if (n_cands[i] > 1) searchable.push_back(i);

  double cur_cost = simulate_assignment(g, c, current.data(), overlap,
                                        hbm_capacity, time_scale,
                                        step_overhead, s);
  double best_cost = cur_cost;
  if (searchable.empty() || budget <= 0) {
    std::copy(best.begin(), best.end(), best_out);
    return best_cost;
  }

  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> uni(0.0, 1.0);
  const int32_t reset_every = std::max(1, budget / 100);

  for (int32_t it = 0; it < budget; ++it) {
    if (it > 0 && it % reset_every == 0 && cur_cost > best_cost) {
      current = best;
      cur_cost = best_cost;
    }

    // one local move: remember (op, old candidate) so reject is O(1)
    int32_t moved_op, old_cand;
    if (enable_propagation && n_edges > 0 && uni(rng) < 0.25) {
      int32_t e = static_cast<int32_t>(rng() % static_cast<uint64_t>(n_edges));
      int32_t src = edge_src[e], dst = edge_dst[e];
      int32_t match = prop_match[prop_offsets[e] + current[src]];
      if (match >= 0) {
        moved_op = dst;
      } else {  // fall back to a random rewrite (reference does the same)
        moved_op = searchable[rng() % searchable.size()];
        match = static_cast<int32_t>(rng() % n_cands[moved_op]);
      }
      old_cand = current[moved_op];
      current[moved_op] = match;
    } else {
      moved_op = searchable[rng() % searchable.size()];
      old_cand = current[moved_op];
      current[moved_op] = static_cast<int32_t>(rng() % n_cands[moved_op]);
    }

    double nxt_cost = simulate_assignment(g, c, current.data(), overlap,
                                          hbm_capacity, time_scale,
                                          step_overhead, s);
    double delta = nxt_cost - cur_cost;
    double temp = std::max(1e-12, alpha * cur_cost);
    if (delta <= 0 || uni(rng) < std::exp(-delta / temp)) {
      cur_cost = nxt_cost;
      if (cur_cost < best_cost) {
        best_cost = cur_cost;
        best = current;
      }
    } else {
      current[moved_op] = old_cand;  // reject
    }
  }

  std::copy(best.begin(), best.end(), best_out);
  return best_cost;
}
