// Internal shared declarations for the native simulator + search.
#ifndef FLEXFLOW_TPU_SIM_CORE_H
#define FLEXFLOW_TPU_SIM_CORE_H

#include <cstdint>
#include <vector>

namespace fftpu {

// One node of the event-simulated task graph.  Mirrors the Python
// SimTask (flexflow_tpu/search/simulator.py) which itself mirrors the
// reference SimTask (include/simulator.h:238-390).
struct Task {
  double duration = 0.0;
  int32_t resource = 0;  // tasks sharing a resource id serialize
  int32_t first_dep = 0; // into TaskGraph::dep_indices
  int32_t n_deps = 0;
};

// Priority-queue event loop over contended resources — the native
// version of TaskGraph.simulate (reference simulator.cc:499-554).
// Ties on ready-time break by insertion order (FIFO), matching the
// Python heapq (ready_time, counter) key.
double simulate(const std::vector<Task> &tasks,
                const std::vector<int32_t> &dep_indices);

}  // namespace fftpu

#endif
