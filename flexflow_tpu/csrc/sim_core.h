// Internal shared declarations for the native simulator + search.
#ifndef FLEXFLOW_TPU_SIM_CORE_H
#define FLEXFLOW_TPU_SIM_CORE_H

#include <cstdint>
#include <vector>

namespace fftpu {

// One node of the event-simulated task graph.  Mirrors the Python
// SimTask (flexflow_tpu/search/simulator.py) which itself mirrors the
// reference SimTask (include/simulator.h:238-390).
struct Task {
  double duration = 0.0;
  int32_t resource = 0;  // tasks sharing a resource id serialize
  int32_t first_dep = 0; // into TaskGraph::dep_indices
  int32_t n_deps = 0;
};

// Priority-queue event loop over contended resources — the native
// version of TaskGraph.simulate (reference simulator.cc:499-554).
// Ties on ready-time break by insertion order (FIFO), matching the
// Python heapq (ready_time, counter) key.
double simulate(const std::vector<Task> &tasks,
                const std::vector<int32_t> &dep_indices);

// Multi-resource variant: a task occupies EVERY resource in its slice
// of res_indices simultaneously (the Python TaskGraph list-resource
// convention — a placed op's device set, an SPMD op holding all
// devices, per-stage pipeline resources).
struct MTask {
  double duration = 0.0;
  int32_t first_res = 0;  // into res_indices
  int32_t n_res = 0;
  int32_t first_dep = 0;  // into dep_indices
  int32_t n_deps = 0;
};

double simulate_multi(const std::vector<MTask> &tasks,
                      const std::vector<int32_t> &res_indices,
                      const std::vector<int32_t> &dep_indices);

}  // namespace fftpu

#endif
