/* flexflow_tpu_c.h — flat C API over the native runtime components.
 *
 * The reference exposes its C++ runtime to Python through a flat
 * extern "C" layer (python/flexflow_c.h: ~130 flexflow_* functions over
 * opaque handles).  In this TPU-native framework the host language is
 * Python/JAX, so the C API covers the components that are native here:
 *
 *   - ffsim_*    event-driven task-graph simulator
 *                (analog of src/runtime/simulator.cc:330-629)
 *   - ffsearch_* MCMC strategy-search annealing loop
 *                (analog of FFModel::optimize, src/runtime/model.cc:1905-1968)
 *   - ffdl_*     prefetching batch gatherer for the data pipeline
 *                (analog of SingleDataLoader, python/flexflow_dataloader.cc)
 *
 * Python binds this header with ctypes (flexflow_tpu/native/__init__.py);
 * every entry point is usable from C as well.
 */
#ifndef FLEXFLOW_TPU_C_H
#define FLEXFLOW_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---------------- simulator ----------------
 * Tasks are given in topological-friendly order (deps may point to any
 * earlier-added or later-added task; the event loop resolves order).
 * resources[i] is an arbitrary small integer id; tasks sharing a
 * resource serialize on it.  deps are CSR: task i depends on tasks
 * dep_indices[dep_indptr[i] .. dep_indptr[i+1]).
 * Returns the makespan (same units as durations). */
double ffsim_simulate(int32_t n_tasks,
                      const double *durations,
                      const int32_t *resources,
                      const int32_t *dep_indptr,
                      const int32_t *dep_indices);

/* ---------------- MCMC strategy search ----------------
 * Per-op candidate costs are precomputed by the caller (the Python cost
 * model, the analog of Op::measure_operator_cost feeding the search).
 *
 * Cost arrays are flattened per (op, candidate): entry
 * cand_offsets[op] + c, for c in [0, n_cands[op]).  Components follow
 * flexflow_tpu.search.cost_model.OpCost: fwd/bwd compute seconds,
 * fwd/bwd collective seconds, gradient-sync seconds, bytes resident.
 *
 * Graph edges are producer->consumer op-index pairs, in the exact
 * iteration order the Python simulator uses (duplicates allowed).
 *
 * prop_match supports the propagation move (reference model.cc:1807-1903):
 * for edge e and source-candidate i, prop_match[prop_offsets[e] + i] is
 * the destination op's candidate with the same axis map, or -1.
 *
 * Device-explicit placements (OpStrategy.device_ids): place_off is a
 * CSR indptr (len total_cands+1) into place_ids; a candidate with a
 * non-empty slice runs only on those device resources, so disjoint
 * placements proceed concurrently while SPMD candidates hold every
 * device.  n_dev is the mesh device count.
 *
 * Pipeline candidates (layer->pipe): pipe_stages[cand] > 1 expands the
 * op into the (microbatch, stage) GPipe schedule over per-stage
 * resources using pipe_mb/pipe_fwd_stage/pipe_bwd_stage/pipe_hop
 * (PipelineCost fields) — the candidate's fwd/bwd/fwd_comm/bwd_comm are
 * ignored, exactly like the Python expansion.
 *
 * init_cand[op] seeds the walk (pure data parallelism by default);
 * best_out[op] receives the best candidate found.  Returns the best
 * simulated step time in seconds (including memory penalty and the
 * calibrated per-step dispatch overhead). */
double ffsearch_mcmc(int32_t n_ops,
                     const int32_t *n_cands,
                     const int32_t *cand_offsets,
                     const double *cost_fwd,
                     const double *cost_bwd,
                     const double *cost_fwd_comm,
                     const double *cost_bwd_comm,
                     const double *cost_sync,
                     const double *cost_mem,
                     const int32_t *place_off,
                     const int32_t *place_ids,
                     const int32_t *pipe_stages,
                     const int32_t *pipe_mb,
                     const double *pipe_fwd_stage,
                     const double *pipe_bwd_stage,
                     const double *pipe_hop,
                     int32_t n_dev,
                     int32_t n_edges,
                     const int32_t *edge_src,
                     const int32_t *edge_dst,
                     const int32_t *prop_offsets,
                     const int32_t *prop_match,
                     int32_t budget,
                     double alpha,
                     uint64_t seed,
                     int32_t enable_propagation,
                     int32_t overlap_backward_sync,
                     double hbm_capacity,
                     double time_scale,
                     double step_overhead,
                     const int32_t *init_cand,
                     int32_t *best_out);

/* Simulate one fixed candidate assignment with the same task-graph
 * construction the search uses (for parity tests / re-costing). */
double ffsearch_simulate_assignment(int32_t n_ops,
                                    const int32_t *cand_offsets,
                                    const double *cost_fwd,
                                    const double *cost_bwd,
                                    const double *cost_fwd_comm,
                                    const double *cost_bwd_comm,
                                    const double *cost_sync,
                                    const double *cost_mem,
                                    const int32_t *place_off,
                                    const int32_t *place_ids,
                                    const int32_t *pipe_stages,
                                    const int32_t *pipe_mb,
                                    const double *pipe_fwd_stage,
                                    const double *pipe_bwd_stage,
                                    const double *pipe_hop,
                                    int32_t n_dev,
                                    int32_t n_edges,
                                    const int32_t *edge_src,
                                    const int32_t *edge_dst,
                                    int32_t overlap_backward_sync,
                                    double hbm_capacity,
                                    double time_scale,
                                    double step_overhead,
                                    const int32_t *assignment);

/* ---------------- data loader ----------------
 * A loader set gathers rows from n_arrays host arrays (equal sample
 * counts) into per-batch contiguous buffers on a background thread,
 * double-buffered — the prefetch analog of the reference's next_batch
 * index-launched copies (flexflow_dataloader.cc:649-740). */
typedef void *ffdl_handle_t;

/* row_bytes[k] = bytes per sample of array k (product of non-batch dims
 * times itemsize; arrays must be C-contiguous). */
ffdl_handle_t ffdl_create(int32_t n_arrays,
                          const void *const *data_ptrs,
                          const int64_t *row_bytes,
                          int64_t n_samples,
                          int32_t batch_size,
                          int32_t drop_last);

/* Begin an epoch over `order` (len n_samples, caller-owned permutation;
 * copied internally).  Restarts prefetching from batch 0. */
void ffdl_start_epoch(ffdl_handle_t h, const int64_t *order);

int32_t ffdl_num_batches(ffdl_handle_t h);

/* Blocks until the next batch is gathered; fills out_ptrs[k] with the
 * internal buffer for array k (valid until the following ffdl_next_batch
 * or ffdl_destroy).  out_rows receives the row count (last batch may be
 * short when drop_last=0).  Returns the batch index, or -1 at epoch end. */
int32_t ffdl_next_batch(ffdl_handle_t h, void **out_ptrs, int32_t *out_rows);

void ffdl_destroy(ffdl_handle_t h);

/* Host-side embedding-bag (reference src/ops/embedding_avx2.cc role in
 * the data pipeline): out[b] = reduce(table[indices[b, :]]) with
 * mode 0=sum, 1=mean; negative/out-of-range indices are padding and are
 * skipped.  indices is (batch, bag_size) row-major; out is (batch, dim). */
void ffdl_embedding_bag(const float *table, int64_t num_entries,
                        int32_t dim, const int64_t *indices, int64_t batch,
                        int32_t bag_size, int32_t mode, float *out);

/* ---------------- misc ---------------- */
const char *flexflow_tpu_native_version(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* FLEXFLOW_TPU_C_H */
