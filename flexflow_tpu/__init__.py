"""flexflow_tpu — a TPU-native distributed DNN training framework with the
capability surface of FlexFlow (reference: dycz0fx/FlexFlow), re-designed
for JAX/XLA/Pallas/pjit.

The reference's architecture (Legion task runtime + CUDA kernels + a
custom mapper enforcing per-op MCMC-searched placements) is replaced by:
graph of ops -> per-op sharding strategies over a jax.sharding.Mesh ->
one jitted SPMD step with XLA-inserted ICI/DCN collectives -> MCMC search
over sharding assignments driven by a calibrated cost model.
"""

from .config import CompMode, FFConfig, FFIterationConfig, ParameterSyncType
from .model import FFModel
from .tensor import Parameter, Tensor
from .core.optimizers import AdamOptimizer, SGDOptimizer
from .parallel.mesh import MachineSpec, default_mesh, make_mesh
from .parallel.pconfig import OpStrategy, ParallelConfig, Strategy

__version__ = "0.1.0"

__all__ = [
    "FFConfig",
    "FFIterationConfig",
    "FFModel",
    "CompMode",
    "ParameterSyncType",
    "Tensor",
    "Parameter",
    "SGDOptimizer",
    "AdamOptimizer",
    "MachineSpec",
    "default_mesh",
    "make_mesh",
    "Strategy",
    "OpStrategy",
    "ParallelConfig",
]
