"""Benchmark driver — prints ONE JSON line.

Default (`python bench.py`): the flagship Transformer-encoder training
step on the real TPU chip — samples/sec/chip and MFU.

`python bench.py --model M` benchmarks the other BASELINE.md configs
(alexnet, inception, dlrm, nmt_lstm) the same way; each prints its own
single JSON line.

Baseline note (BASELINE.md): the reference repo commits no numbers; its
north star is "MFU within 10% of FlexFlow's own V100-class results".
FlexFlow's V100-era transformer training lands around 30% MFU (MLSys'19
workloads, fp32 cuBLAS); we take mfu_baseline = 0.30 and report
vs_baseline = our_mfu / 0.30 (>1.0 beats the reference).
"""

import argparse
import json
import time

import numpy as np

MFU_BASELINE = 0.30
PEAK_FLOPS = {
    # bf16 peak per chip
    "v5litepod": 197e12,  # v5e
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e12,  # nominal, so the script degrades gracefully off-TPU
}


def detect_peak():
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind or k in kind.replace(" ", ""):
            return v
    return PEAK_FLOPS["cpu"] if dev.platform == "cpu" else 197e12


def build(model: str):
    """Returns (ff, batch_data), compiled and ready to train."""
    import jax.numpy as jnp
    from flexflow_tpu import FFConfig, SGDOptimizer
    from flexflow_tpu import models as zoo

    rng = np.random.RandomState(0)
    cfg = FFConfig()
    if model == "transformer":
        batch, seq, hidden = 32, 512, 512
        cfg.batch_size = batch
        ff = zoo.build_transformer(cfg, batch_size=batch, seq_len=seq,
                                   hidden=hidden, num_heads=8, num_layers=6,
                                   ff_dim=2048, num_classes=10,
                                   dtype=jnp.bfloat16)
        data = {"input": jnp.asarray(
            rng.randn(batch, seq, hidden), jnp.bfloat16),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    elif model == "alexnet":
        batch = 256
        cfg.batch_size = batch
        ff = zoo.build_alexnet(cfg, batch_size=batch)
        data = {"input": jnp.asarray(
            rng.randn(batch, 3, 32, 32), jnp.float32),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    elif model == "inception":
        batch = 32
        cfg.batch_size = batch
        ff = zoo.build_inception_v3(cfg, batch_size=batch, image_size=299)
        data = {"input": jnp.asarray(
            rng.randn(batch, 3, 299, 299), jnp.float32),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    elif model == "dlrm":
        batch = 1024
        cfg.batch_size = batch
        vocabs = (1000000,) * 8
        ff = zoo.build_dlrm(cfg, batch_size=batch,
                            embedding_vocab_sizes=vocabs)
        data = {"dense_features": jnp.asarray(
            rng.randn(batch, 13), jnp.float32),
            "label": jnp.asarray(
                rng.rand(batch, 1) > 0.5, jnp.float32)}
        for i in range(len(vocabs)):
            data[f"sparse_{i}"] = jnp.asarray(
                rng.randint(0, vocabs[i], (batch, 1)), jnp.int32)
    elif model == "nmt_lstm":
        batch, seq = 64, 40
        cfg.batch_size = batch
        ff = zoo.build_nmt_lstm(cfg, batch_size=batch, seq_len=seq)
        data = {"input": jnp.asarray(
            rng.randint(0, 32000, (batch, seq)), jnp.int32),
            "label": jnp.asarray(rng.randint(0, 32000, (batch,)),
                                 jnp.int32)}
    else:
        raise SystemExit(f"unknown --model {model}")
    loss = ("mean_squared_error" if model == "dlrm"
            else "sparse_categorical_crossentropy")
    ff.compile(optimizer=SGDOptimizer(lr=0.01), loss_type=loss, metrics=[])
    return ff, data


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="transformer",
                    choices=["transformer", "alexnet", "inception", "dlrm",
                             "nmt_lstm"])
    ap.add_argument("--steps", type=int, default=40)
    args = ap.parse_args()

    ff, batch_data = build(args.model)
    batch = next(iter(batch_data.values())).shape[0]
    fwd_flops = sum(op.flops() for op in ff.ops)
    # Standard MFU accounting: step = fwd + 2x-fwd backward. (The search
    # cost model prices attention backward at 4x because flash RECOMPUTES
    # probabilities — recompute is overhead, not useful work, so it is
    # deliberately excluded here; counting it would inflate MFU.)
    step_flops = 3.0 * fwd_flops

    # warmup (includes compile). NOTE: through the axon tunnel
    # block_until_ready does not sync; only a device->host transfer does,
    # so we force a scalar fetch to delimit timing regions.
    for _ in range(3):
        m = ff.train_batch(batch_data)
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(args.steps):
        m = ff.train_batch(batch_data)
    float(m["loss"])  # drains the queued steps
    dt = (time.perf_counter() - t0) / args.steps

    samples_per_sec = batch / dt
    achieved = step_flops / dt
    mfu = achieved / detect_peak()
    print(json.dumps({
        "metric": f"{args.model}_train_samples_per_sec_per_chip"
        if args.model != "transformer"
        else "transformer_encoder_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(mfu / MFU_BASELINE, 4),
    }))


if __name__ == "__main__":
    main()
