"""Benchmark driver — prints ONE JSON line on stdout, progress on stderr.

Default (`python bench.py`): the flagship Transformer-encoder training
step — samples/sec/chip and MFU vs the 0.30-MFU FlexFlow-V100 baseline
(BASELINE.md: the reference commits no numbers; its north star is "MFU
within 10% of FlexFlow's own V100-class results").

Robustness (round-1 postmortem: the axon TPU tunnel's backend init can
take many minutes or hang, and a single env hiccup zeroed the round's
perf evidence):
  - the parent stages attempts in SUBPROCESSES, each with its own
    timeout: full-size TPU run -> small-preset TPU run -> tiny CPU run,
    so *some* measured number always lands (rc=0);
  - each child prints per-phase progress (init/build/compile/steps) to
    stderr with timestamps;
  - `--deadline` (or BENCH_DEADLINE_S) bounds the whole ladder.

`python bench.py --model M` benchmarks the other BASELINE.md configs
(alexnet, inception, dlrm, nmt_lstm); `--all` sweeps all five and
writes bench_all.json (the per-round evidence artifact), still printing
the flagship line last.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

MFU_BASELINE = 0.30
# bandwidth-bound models (DLRM) are scored against the HBM roofline with
# their OWN baseline constant so vs_baseline keeps consistent units
# ("fraction of the target utilization for this model's bound resource")
HBM_UTIL_BASELINE = 0.30
PEAK_FLOPS = {
    # bf16 peak per chip
    "v5litepod": 197e12,  # v5e
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "v6 lite": 918e12,  # v6e device_kind reads "TPU v6 lite"
    "cpu": 1e12,  # nominal, so the script degrades gracefully off-TPU
}
PEAK_HBM_BW = {
    # bytes/s per chip
    "v5litepod": 819e9,
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v4": 1228e9,
    "v6e": 1640e9,
    "v6 lite": 1640e9,
    "cpu": 50e9,
}

MODELS = ["transformer", "alexnet", "inception", "dlrm", "nmt_lstm"]

# preset -> per-model shape overrides (batch, plus model-specific dims)
PRESETS = ("full", "small", "tiny")


def log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


T0 = time.perf_counter()


def detect_peak(table=PEAK_FLOPS, default=197e12):
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    for k, v in table.items():
        if k in kind or k in kind.replace(" ", ""):
            return v
    return table["cpu"] if dev.platform == "cpu" else default


def step_bytes(ff, batch=None):
    """-> (bytes, basis_label). HBM bytes one training step moves — the
    numerator for a roofline utilization on bandwidth-bound models
    (DLRM), where MFU is structurally ~0 for any framework on any
    hardware.

    Primary source: XLA's OWN cost analysis of the compiled step
    ("bytes accessed" over the post-fusion HLO) — not a hand model.
    Falls back to an approximate analytic count (weights ~4 passes,
    activations ~3, sparse-updated embedding rows ~6) only when the
    compiled analysis is unavailable."""
    if batch is not None:
        try:
            from flexflow_tpu.utils.profiling import hlo_cost
            b = float(hlo_cost(ff, batch).get("bytes accessed", 0.0))
            if b > 0:
                return b, "hbm_roofline_xla"
        except Exception as e:  # pragma: no cover - backend-specific
            log(f"hlo bytes unavailable ({e}); using analytic estimate")
    from flexflow_tpu.ops.embedding import DistributedEmbedding, Embedding
    wbytes = abytes = ebytes = 0.0
    for op in ff.ops:
        if isinstance(op, (Embedding, DistributedEmbedding)):
            idx = op.inputs[0].shape
            bag = idx[-1] if len(idx) > 1 else 1
            ntab = getattr(op, "num_tables", 1)
            ebytes += ntab * idx[0] * bag * op.out_dim * 4
            continue
        for spec in op.weight_specs().values():
            n = 1
            for s in spec.shape:
                n *= s
            wbytes += n * 4
        for t in op.outputs:
            abytes += t.num_elements * jnp_dtype_size(t.dtype)
    return 4.0 * wbytes + 3.0 * abytes + 6.0 * ebytes, \
        "hbm_roofline_approx"


def positive_int_env(name: str, default: int) -> int:
    """Sweep-knob env var -> positive int, failing loudly on junk (a
    typo'd knob in a session script must show in the evidence log as a
    message, not a traceback)."""
    v = os.environ.get(name)
    if not v:
        return default
    try:
        n = int(v)
    except ValueError:
        raise SystemExit(f"{name}={v!r} is not an integer")
    if n <= 0:
        raise SystemExit(f"{name} must be positive, got {n}")
    return n


def jnp_dtype_size(dt) -> int:
    import numpy as _np
    try:
        return _np.dtype(dt).itemsize
    except TypeError:
        return 2 if "bfloat16" in str(dt) else 4


def build(model: str, preset: str):
    """Returns (ff, batch_data), compiled and ready to train."""
    import jax.numpy as jnp
    from flexflow_tpu import FFConfig, SGDOptimizer
    from flexflow_tpu import models as zoo

    rng = np.random.RandomState(0)
    cfg = FFConfig()
    # conv compute-layout A/B knob (tools/tpu_session.sh sweeps it)
    layout = os.environ.get("BENCH_CONV_LAYOUT")
    if layout:
        cfg.conv_layout = layout
    # sibling-conv batching A/B knob (default on; the session queue
    # captures the merged-vs-unmerged delta on chip)
    if os.environ.get("BENCH_SIBLING_FUSION") == "0":
        cfg.sibling_conv_fusion = False

    def _b(default):
        # BENCH_BATCH: sweep knob for per-chip batch (MFU is
        # batch-sensitive on conv models; tools/tpu_session.sh A/Bs it).
        # Child-mode only — main() strips it in ladder mode so the
        # preset fallback keeps reducing batch on OOM/timeouts.
        return positive_int_env("BENCH_BATCH", default)

    if model == "transformer":
        batch, seq, hidden, layers, ffd = {
            "full": (32, 512, 512, 6, 2048),
            "small": (16, 256, 512, 4, 2048),
            "tiny": (8, 64, 128, 2, 256),
        }[preset]
        batch = _b(batch)
        cfg.batch_size = batch
        ff = zoo.build_transformer(cfg, batch_size=batch, seq_len=seq,
                                   hidden=hidden, num_heads=8,
                                   num_layers=layers, ff_dim=ffd,
                                   num_classes=10, dtype=jnp.bfloat16)
        data = {"input": jnp.asarray(
            rng.randn(batch, seq, hidden), jnp.bfloat16),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    elif model == "alexnet":
        batch = _b({"full": 256, "small": 128, "tiny": 16}[preset])
        cfg.batch_size = batch
        # bf16 activations (weights f32): MXU-native mixed precision,
        # same mode the transformer config benches in
        ff = zoo.build_alexnet(cfg, batch_size=batch, dtype=jnp.bfloat16)
        data = {"input": jnp.asarray(
            rng.randn(batch, 3, 32, 32), jnp.bfloat16),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    elif model == "inception":
        batch = _b({"full": 32, "small": 16, "tiny": 4}[preset])
        size = {"full": 299, "small": 299, "tiny": 75}[preset]
        cfg.batch_size = batch
        ff = zoo.build_inception_v3(cfg, batch_size=batch, image_size=size,
                                    dtype=jnp.bfloat16)
        data = {"input": jnp.asarray(
            rng.randn(batch, 3, size, size), jnp.bfloat16),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    elif model == "dlrm":
        # Criteo-like shape (reference run scripts: 26 sparse features,
        # ~1M vocab, bag 1, examples/cpp/DLRM/run_summit.sh); large batch
        # because DLRM is bandwidth/latency-bound, not FLOPs-bound — at
        # batch 1024 even a perfect step is <0.1ms of HBM traffic and
        # every framework measures overhead, not hardware
        batch = _b({"full": 8192, "small": 2048, "tiny": 64}[preset])
        vocab = {"full": 1000000, "small": 100000, "tiny": 1000}[preset]
        ntab = {"full": 26, "small": 26, "tiny": 8}[preset]
        cfg.batch_size = batch
        vocabs = (vocab,) * ntab
        # BENCH_DLRM_STACKED=1: ONE vmapped gather over a (T, vocab,
        # dim) kernel instead of 26 separate gathers — the executable
        # placement form. Default stays the separate-table layout the
        # committed sweep measured (CPU-tiny A/B favored separate;
        # tools/tpu_session.sh decides at bench scale on chip).
        stacked = os.environ.get("BENCH_DLRM_STACKED", "0") == "1"
        ff = zoo.build_dlrm(cfg, batch_size=batch,
                            embedding_vocab_sizes=vocabs,
                            stacked_tables=stacked)
        data = {"dense_features": jnp.asarray(
            rng.randn(batch, 13), jnp.float32),
            "label": jnp.asarray(
                rng.rand(batch, 1) > 0.5, jnp.float32)}
        for i in range(len(vocabs)):
            data[f"sparse_{i}"] = jnp.asarray(
                rng.randint(0, vocabs[i], (batch, 1)), jnp.int32)
    elif model == "nmt_lstm":
        # batch 256: the recurrent h@Wh GEMM's M dim IS the batch — at 64
        # it fills half the MXU sublanes; 256 fills the pipeline (the
        # reference nmt trains large global batches across GPUs too)
        batch, seq = {"full": (256, 40), "small": (64, 40),
                      "tiny": (8, 10)}[preset]
        batch = _b(batch)
        cfg.batch_size = batch
        ff = zoo.build_nmt_lstm(cfg, batch_size=batch, seq_len=seq,
                                dtype=jnp.bfloat16)
        data = {"input": jnp.asarray(
            rng.randint(0, 32000, (batch, seq)), jnp.int32),
            "label": jnp.asarray(rng.randint(0, 32000, (batch,)),
                                 jnp.int32)}
    else:
        raise SystemExit(f"unknown --model {model}")
    loss = ("mean_squared_error" if model == "dlrm"
            else "sparse_categorical_crossentropy")
    ff.compile(optimizer=SGDOptimizer(lr=0.01), loss_type=loss, metrics=[])
    return ff, data


def run_child(model: str, preset: str, steps: int) -> int:
    """Measure in THIS process; print the JSON line. Progress to stderr."""
    log(f"child start: model={model} preset={preset}")
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        # the image's sitecustomize sets jax_platforms="axon,cpu" via
        # jax.config, which beats the JAX_PLATFORMS env var — override
        # the same way (tests/conftest.py does identically)
        jax.config.update("jax_platforms", "cpu")
    log("initializing backend (jax.devices)...")
    devs = jax.devices()
    platform = devs[0].platform
    log(f"backend up: {devs[0].device_kind} ({platform}) x{len(devs)}")
    if platform != "tpu" and not os.environ.get("BENCH_FORCE_CPU"):
        # the sitecustomize registers platforms "axon,cpu": a FAST axon
        # failure silently lands here on CPU with rc=0, which let a
        # dead-tunnel session arm look measured. rc=75 (EX_TEMPFAIL —
        # distinct from pytest's 0-5 and timeout's 124/137) is the
        # shared tunnel-signature code (tools/_platform.py, note_rc in
        # tools/tpu_session.sh); the ladder's CPU rung sets
        # BENCH_FORCE_CPU so the deliberate fallback is unaffected.
        log(f"child expected tpu but backend is {platform} — exiting "
            f"rc=75 without measuring (tunnel down? set "
            f"BENCH_FORCE_CPU=1 to measure on CPU deliberately)")
        return 75

    ff, batch_data = build(model, preset)
    log("model built + compiled graph-side; warming up (jit compile)...")
    batch = next(iter(batch_data.values())).shape[0]
    fwd_flops = sum(op.flops() for op in ff.ops)
    # Standard MFU accounting: step = fwd + 2x-fwd backward. (The search
    # cost model prices attention backward at 4x because flash RECOMPUTES
    # probabilities — recompute is overhead, not useful work, so it is
    # deliberately excluded here; counting it would inflate MFU.)
    step_flops = 3.0 * fwd_flops

    # warmup (includes compile). NOTE: through the axon tunnel
    # block_until_ready does not sync; only a device->host transfer does,
    # so we force a scalar fetch to delimit timing regions.
    t_c = time.perf_counter()
    nbytes_basis = None
    if model == "dlrm":
        # the roofline byte source compiles the single-step program AOT;
        # doing it INSTEAD of the single-step warmup keeps total
        # compiles at two (single + scanned multi), same as every other
        # model — the multi-step warmup below still warms the device
        nbytes_basis = step_bytes(ff, batch_data)
        log(f"single-step cost probe ({nbytes_basis[1]}) in "
            f"{time.perf_counter() - t_c:.1f}s")
    else:
        m = ff.train_batch(batch_data)
        float(m["loss"])
        log(f"first step (compile) done in "
            f"{time.perf_counter() - t_c:.1f}s")
    # measure through the scanned multi-step dispatch (train_batches =
    # the Legion trace-replay analog): one host round trip per DISPATCH
    # of `per_dispatch` steps, so tunnel/dispatch latency (~4ms/call via
    # axon) is amortized the same way begin/end_trace amortizes Legion
    # dependence analysis in the reference hot loop (alexnet.cc:106-111)
    per_dispatch = min(positive_int_env("BENCH_PER_DISPATCH", 10), steps)
    # two candidate groupings: the K-step program, then 1 step/dispatch.
    # The K-step program double-buffers the carried params, so at param
    # scales near HBM capacity (DLRM 26x1M tables) it can OOM where the
    # single-step program (true in-place donation) fits.
    for pd_try in dict.fromkeys((per_dispatch, 1)):
        try:
            per_dispatch = pd_try
            group = ff.stage_batches([batch_data] * per_dispatch)
            t_c = time.perf_counter()
            m = ff.train_batches(group)
            float(np.sum(np.asarray(m["loss"], dtype=np.float64)))
            log(f"{per_dispatch}-step compile done in "
                f"{time.perf_counter() - t_c:.1f}s")
            break
        except Exception as exc:  # noqa: BLE001
            msg = str(exc).lower()
            # XLA/TPU allocators phrase OOM three ways: "ran out of
            # memory", "out of memory while trying to allocate", and
            # bare RESOURCE_EXHAUSTED status strings
            oom = ("out of memory" in msg or "resource_exhausted" in msg
                   or "resource exhausted" in msg)
            if pd_try == 1 or not oom:
                raise
            log(f"multi-step scan OOM'd "
                f"({str(exc).splitlines()[0][:120]}); "
                f"falling back to per_dispatch=1")
            # an EXECUTION-time OOM has already consumed the donated
            # state buffers ("Array has been deleted" on reuse) —
            # rebuild fresh; build() is deterministic (seeded)
            ff, batch_data = build(model, preset)
    n_disp = max(1, steps // per_dispatch)
    log(f"warmup done; timing {n_disp} dispatches x {per_dispatch} steps...")

    # best-of-3 timed passes: the remote-TPU tunnel adds multi-ms jitter
    # and minute-scale slow periods (identical runs observed 2x apart) —
    # the minimum over repeated async passes is the robust estimate of
    # sustained device throughput
    def timed_pass():
        t0 = time.perf_counter()
        for _ in range(n_disp):
            m = ff.train_batches(group)
        float(np.sum(np.asarray(m["loss"], dtype=np.float64)))  # drain
        return (time.perf_counter() - t0) / (n_disp * per_dispatch)

    dts = [timed_pass() for _ in range(3)]
    dt = min(dts)
    log(f"steps done: {dt * 1e3:.2f} ms/step "
        f"(best of {[round(d * 1e3, 2) for d in dts]})")

    samples_per_sec = batch / dt
    achieved = step_flops / dt
    mfu = achieved / detect_peak()
    extra = {"mfu": round(mfu, 4), "ms_per_step": round(dt * 1e3, 3),
             "preset": preset, "platform": platform,
             "batch": batch, "steps": steps,
             "per_dispatch": per_dispatch}
    util = mfu
    util_baseline = MFU_BASELINE
    extra["util_basis"] = "mfu"
    if model == "dlrm":
        # bandwidth-bound: score distance to the HBM roofline, not the
        # MXU one (MFU stays in extras; DLRM's useful work per byte is
        # tiny by construction — embedding rows dominate). vs_baseline
        # stays unit-consistent: it divides the roofline utilization by
        # a BANDWIDTH baseline constant (HBM_UTIL_BASELINE), and the
        # basis is declared in the JSON (util_basis). The byte count is
        # an approximate model (step_bytes docstring).
        nbytes, basis = nbytes_basis
        hbm_util = nbytes / dt / detect_peak(PEAK_HBM_BW, 819e9)
        extra["hbm_util"] = round(hbm_util, 4)
        if hbm_util >= mfu:
            util = hbm_util
            util_baseline = HBM_UTIL_BASELINE
            extra["util_basis"] = basis
    extra["captured"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    suffix = "" if platform != "cpu" else "_cpu_fallback"
    metric = (f"{model}_train_samples_per_sec_per_chip"
              if model != "transformer"
              else "transformer_encoder_train_samples_per_sec_per_chip")
    print(json.dumps({
        "metric": metric + suffix,
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(util / util_baseline, 4),
        "extra": extra,
    }), flush=True)
    return 0


def try_child(model, preset, steps, timeout, force_cpu=False):
    """Run one attempt in a subprocess; returns parsed JSON dict or None."""
    env = dict(os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_FORCE_CPU"] = "1"
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--model", model, "--preset", preset, "--steps", str(steps)]
    log(f"attempt: preset={preset} cpu={force_cpu} timeout={timeout:.0f}s")
    try:
        r = subprocess.run(cmd, env=env, timeout=timeout,
                           stdout=subprocess.PIPE, stderr=sys.stderr)
    except subprocess.TimeoutExpired:
        log(f"attempt timed out after {timeout:.0f}s")
        return None
    if r.returncode != 0:
        log(f"attempt failed rc={r.returncode}")
        return None
    for line in reversed(r.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    log("attempt produced no JSON line")
    return None


_tpu_probe_result = None  # (ok: bool, reason: str)


def probe_tpu(timeout=120):
    """Can the ambient (axon/TPU) backend come up at all? Returns
    (ok, reason) where `reason` distinguishes the failure modes a
    stale-marked record must explain (round-5 postmortem: BENCH_r*
    trajectories silently mixed stale TPU and live CPU numbers with no
    WHY): a probe TIMEOUT means the relay is down or the lease is stuck
    (jax.devices() hangs forever on a dead relay — hence the
    subprocess + hard timeout), a fast CPU resolution means the axon
    plugin failed over instantly (no lease / plugin error), a nonzero
    rc means backend init crashed outright. Cached across models in an
    --all sweep; `--probe-timeout` tunes the window."""
    global _tpu_probe_result
    if _tpu_probe_result is not None:
        return _tpu_probe_result
    log(f"probing TPU backend (timeout {timeout:.0f}s)...")
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "print(d.platform, d.device_kind)"],
            timeout=timeout, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        out = r.stdout.decode().strip()
        # the sitecustomize registers platforms "axon,cpu" — a fast axon
        # failure still exits 0 on the CPU fallback, so check the
        # platform actually resolved, not just the return code
        if r.returncode != 0:
            _tpu_probe_result = (
                False, f"probe rc={r.returncode}: backend init crashed")
        elif not out or out.startswith("cpu"):
            _tpu_probe_result = (
                False, f"backend resolved to {out or 'nothing'!s} "
                f"(axon fast-fail: no TPU lease / plugin error)")
        else:
            _tpu_probe_result = (True, f"ok: {out}")
    except subprocess.TimeoutExpired:
        _tpu_probe_result = (
            False, f"probe timed out after {timeout:.0f}s "
            f"(relay down or lease stuck)")
    ok, reason = _tpu_probe_result
    log(f"TPU backend {'OK' if ok else 'unavailable'}: {reason}")
    return _tpu_probe_result


def probe_reason():
    """The cached probe verdict's reason ('' before any probe ran)."""
    return _tpu_probe_result[1] if _tpu_probe_result else ""


def run_ladder(model, steps, deadline_at, allow_cpu_fallback=True,
               probe_timeout=120, cpu_only=False):
    """probe -> TPU full (retry) -> TPU small -> CPU tiny; never returns
    empty-handed while the CPU fallback can run. Returns dict|None.
    `cpu_only` skips the probe and the TPU rungs entirely (a deliberate
    CPU measurement, not a fallback — finalize() keeps it fresh)."""
    remaining = lambda: deadline_at - time.perf_counter()  # noqa: E731
    if cpu_only:
        global _tpu_probe_result
        _tpu_probe_result = (False, "cpu-only requested (--cpu-only)")
        return try_child(model, "tiny", max(5, steps // 4),
                         max(30, remaining()), force_cpu=True)
    # reserve time for the guaranteed CPU fallback
    reserve = 150 if allow_cpu_fallback else 0
    ok, _why = probe_tpu(min(probe_timeout,
                             max(30, remaining() - reserve)))
    if ok:
        # backend comes up: give full-size runs real budgets, retry once
        # (transient tunnel hiccups), then degrade to the small preset
        attempts = [("full", 420), ("full", 420), ("small", 300)]
    else:
        # backend didn't come up in the probe window: one hail-mary full
        # attempt (init may just be slow), then straight to CPU
        attempts = [("full", 300)]
    for preset, cap in attempts:
        budget = remaining() - reserve
        if budget < 60:
            break
        res = try_child(model, preset, steps, min(cap, budget), False)
        if res:
            return res
    if allow_cpu_fallback and remaining() > 30:
        res = try_child(model, "tiny", max(5, steps // 4),
                        remaining(), force_cpu=True)
        if res:
            return res
    return None


def _bench_all_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_all.json")


def _is_tpu_result(res):
    return bool(res) and str(
        res.get("extra", {}).get("platform", "")).startswith("tpu")


def last_committed_tpu(model):
    """Last TPU-measured result for `model` from the committed
    bench_all.json sweep, or None. Timestamp falls back to the file's
    git commit date for sweeps captured before `captured` stamping.

    Why this exists (round-2 postmortem): a dead tunnel at capture time
    made BENCH_r02.json report a CPU tiny-preset number (MFU 0.043) for
    a framework whose committed sweep measured MFU 0.33 on chip. The
    reference never loses committed strategy files to a dead node
    (strategy.cc:95-189); committed measurements deserve the same."""
    global _bench_all_cache
    if _bench_all_cache is None:
        try:
            with open(_bench_all_path()) as f:
                _bench_all_cache = json.load(f)
        except (OSError, json.JSONDecodeError):
            _bench_all_cache = {}
    entry = _bench_all_cache.get(model)
    if not _is_tpu_result(entry):
        return None
    if "captured" not in entry.get("extra", {}):
        stamp = _bench_all_git_stamp()
        if stamp:
            entry.setdefault("extra", {})["captured"] = stamp
    return entry


_bench_all_cache = None
_git_stamp_cache = None


def _bench_all_git_stamp():
    """Commit date of bench_all.json, normalized to UTC 'Z' so captured
    stamps from git and from fresh runs sort consistently."""
    global _git_stamp_cache
    if _git_stamp_cache is not None:
        return _git_stamp_cache
    stamp = ""
    try:
        r = subprocess.run(
            ["git", "log", "-1", "--format=%cI", "--", _bench_all_path()],
            cwd=os.path.dirname(_bench_all_path()),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, timeout=10)
        raw = r.stdout.decode().strip()
        if raw:
            from datetime import datetime, timezone
            stamp = datetime.fromisoformat(raw).astimezone(
                timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
    except Exception:
        pass
    _git_stamp_cache = stamp
    return stamp


def finalize(model, res, cpu_only=False):
    """Choose the headline JSON line: a fresh TPU measurement wins; a
    CPU fallback (or total failure) is REPLACED by the last committed
    TPU sweep entry, stale-marked + timestamped + annotated with WHY
    the TPU was unreachable (probe_reason: relay down vs lease stuck vs
    fast axon fail), with the fresh CPU number attached as a liveness
    signal. Under --cpu-only the CPU number IS the requested
    measurement and is returned fresh, never stale-replaced."""
    if _is_tpu_result(res):
        return res
    if cpu_only:
        return res
    hist = last_committed_tpu(model)
    if hist is None:
        return res  # no history: the CPU fallback is all we have
    hist = dict(hist)
    hist["extra"] = dict(hist.get("extra", {}))
    hist["extra"]["stale"] = True
    hist["extra"]["stale_reason"] = probe_reason() or "unknown"
    # staleness must survive parsers that ignore `extra`: surface it at
    # top level too
    hist["stale"] = True
    if res:
        hist["extra"]["cpu_liveness"] = {
            "value": res.get("value"),
            "vs_baseline": res.get("vs_baseline"),
            "ms_per_step": res.get("extra", {}).get("ms_per_step"),
            "captured": res.get("extra", {}).get("captured"),
        }
    else:
        hist["extra"]["cpu_liveness"] = None
    log(f"{model}: TPU unreachable now "
        f"({hist['extra']['stale_reason']}); emitting last committed "
        f"TPU sweep (captured {hist['extra'].get('captured', '?')}) "
        f"stale-marked, CPU liveness attached")
    return hist


def merge_bench_all(results):
    """Write bench_all.json without letting a dead tunnel erase history:
    per model, a fresh TPU result overwrites; a CPU fallback/None keeps
    the existing TPU entry (stale-marked, with the probe's WHY) and
    records the fallback under extra.cpu_liveness via finalize().
    Committed entries for models NOT in this sweep survive untouched
    (history is merged into, never rebuilt from scratch). --cpu-only
    sweeps never reach this function (main() skips the merge so a
    deliberate CPU diagnostic cannot overwrite TPU history)."""
    try:
        with open(_bench_all_path()) as f:
            merged = json.load(f)
    except (OSError, json.JSONDecodeError):
        merged = {}
    merged.update({m: finalize(m, r) for m, r in results.items()})
    with open(_bench_all_path(), "w") as f:
        json.dump(merged, f, indent=2)
    return merged


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="transformer", choices=MODELS)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--preset", default="full", choices=PRESETS)
    ap.add_argument("--child", action="store_true",
                    help="internal: measure in-process, no retry ladder")
    ap.add_argument("--all", action="store_true",
                    help="sweep all five BASELINE.md configs; write "
                         "bench_all.json; print the flagship line last")
    ap.add_argument("--deadline", type=float,
                    default=float(os.environ.get("BENCH_DEADLINE_S", 900)))
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get(
                        "BENCH_PROBE_TIMEOUT_S", 120)),
                    help="seconds to wait for the TPU backend probe "
                         "before declaring the tunnel dead")
    ap.add_argument("--cpu-only", action="store_true",
                    help="skip the TPU probe and rungs; measure the "
                         "tiny preset on CPU deliberately (result is "
                         "fresh, never stale-replaced)")
    args = ap.parse_args()

    if args.child:
        return run_child(args.model, args.preset, args.steps)

    # ladder mode owns the preset fallback: a pinned sweep batch would
    # defeat the full->small->tiny degradation (every rung would OOM the
    # same way), so the knob is honored only under --child
    if "BENCH_BATCH" in os.environ:
        log(f"ignoring BENCH_BATCH={os.environ['BENCH_BATCH']} in "
            f"ladder mode (use --child for batch sweeps)")
        del os.environ["BENCH_BATCH"]

    deadline_at = time.perf_counter() + args.deadline
    if args.all:
        results = {}
        others = [m for m in MODELS if m != "transformer"]
        # flagship FIRST: tunnel windows die unpredictably (observed
        # lifetimes 2-29 min), and whatever ran before the death is
        # what the round keeps — the scoreboard item is the flagship's
        # number, so it must not be the one at risk. Its slot is
        # bounded so a healthy window still reaches the other four;
        # each of those needs reserve(150) + one real attempt, so the
        # slot floors at 400s — a short --deadline stretches rather
        # than silently demoting every model to the CPU fallback.
        results["transformer"] = run_ladder(
            "transformer", args.steps,
            time.perf_counter()
            + max(400.0, min(700.0, args.deadline * 0.3)),
            probe_timeout=args.probe_timeout, cpu_only=args.cpu_only)
        per = max(400.0, (deadline_at - time.perf_counter() - 100)
                  / len(others))
        for m in others:
            results[m] = run_ladder(m, args.steps,
                                    time.perf_counter() + per,
                                    probe_timeout=args.probe_timeout,
                                    cpu_only=args.cpu_only)
        # exit 0 only when EVERY config measured fresh ON CHIP this
        # run: the session script gates its full-queue-done sentinel on
        # this rc, and bench's internal ladder hides tunnel deaths
        # behind CPU/stale fallbacks (exit-0-if-any-fresh let a
        # mid-sweep tunnel death count as a completed sweep)
        all_fresh_tpu = all(_is_tpu_result(v) for v in results.values()) \
            or (args.cpu_only and all(bool(v) for v in results.values()))
        if args.cpu_only:
            # a deliberate CPU diagnostic must never overwrite the
            # committed TPU history that last_committed_tpu / the
            # stale-replacement ladder depend on
            log("--cpu-only: not merging into bench_all.json "
                "(committed TPU history preserved)")
            results = {m: finalize(m, r, cpu_only=True)
                       for m, r in results.items()}
        else:
            results = merge_bench_all(results)
        log(f"sweep done: { {k: bool(v) for k, v in results.items()} } "
            f"all_fresh_tpu={all_fresh_tpu}")
        flag = results["transformer"]
        if flag:
            print(json.dumps(flag), flush=True)
            # stale history keeps the perf story on stdout, but the
            # exit code still reports whether THIS run measured the
            # full sweep on chip
            return 0 if all_fresh_tpu else 1
        return 1

    fresh = run_ladder(args.model, args.steps, deadline_at,
                       probe_timeout=args.probe_timeout,
                       cpu_only=args.cpu_only)
    res = finalize(args.model, fresh, cpu_only=args.cpu_only)
    if res:
        print(json.dumps(res), flush=True)
        return 0 if fresh else 1
    log("all attempts failed")
    return 1


if __name__ == "__main__":
    sys.exit(main())
