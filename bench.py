"""Benchmark driver — prints ONE JSON line.

Measures the flagship Transformer-encoder training step on the real TPU
chip: samples/sec/chip and MFU.

Baseline note (BASELINE.md): the reference repo commits no numbers; its
north star is "MFU within 10% of FlexFlow's own V100-class results".
FlexFlow's V100-era transformer training lands around 30% MFU (MLSys'19
workloads, fp32 cuBLAS); we take mfu_baseline = 0.30 and report
vs_baseline = our_mfu / 0.30 (>1.0 beats the reference).
"""

import json
import time

import numpy as np

MFU_BASELINE = 0.30
PEAK_FLOPS = {
    # bf16 peak per chip
    "v5litepod": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e12,  # nominal, so the script degrades gracefully off-TPU
}


def detect_peak():
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower().replace(" ", "")
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return PEAK_FLOPS["cpu"] if dev.platform == "cpu" else 197e12


def main():
    import jax
    import jax.numpy as jnp
    from flexflow_tpu import FFConfig, SGDOptimizer
    from flexflow_tpu.models.transformer import build_transformer

    batch, seq, hidden, heads, layers, ffd = 32, 512, 512, 8, 6, 2048
    cfg = FFConfig()
    cfg.batch_size = batch
    ff = build_transformer(cfg, batch_size=batch, seq_len=seq, hidden=hidden,
                           num_heads=heads, num_layers=layers, ff_dim=ffd,
                           num_classes=10, dtype=jnp.bfloat16)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=[])

    fwd_flops = sum(op.flops() for op in ff.ops)
    step_flops = 3.0 * fwd_flops  # fwd + ~2x bwd

    rng = np.random.RandomState(0)
    x = rng.randn(batch, seq, hidden).astype(np.float32)
    y = rng.randint(0, 10, (batch,)).astype(np.int32)
    batch_data = {"input": jnp.asarray(x, jnp.bfloat16), "label": jnp.asarray(y)}

    # warmup (includes compile). NOTE: through the axon tunnel
    # block_until_ready does not sync; only a device->host transfer does,
    # so we force a scalar fetch to delimit timing regions.
    for _ in range(3):
        m = ff.train_batch(batch_data)
    float(m["loss"])

    steps = 40
    t0 = time.perf_counter()
    for _ in range(steps):
        m = ff.train_batch(batch_data)
    float(m["loss"])  # drains the queued steps
    dt = (time.perf_counter() - t0) / steps

    samples_per_sec = batch / dt
    achieved = step_flops / dt
    mfu = achieved / detect_peak()
    print(json.dumps({
        "metric": "transformer_encoder_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(mfu / MFU_BASELINE, 4),
    }))


if __name__ == "__main__":
    main()
