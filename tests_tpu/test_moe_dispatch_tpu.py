"""On-chip MoE dispatch A/B: dense GShard masks vs sorted-scatter
routing (round 4, VERDICT r3 #8). Correctness parity is pinned by the
CPU suite (tests/test_expert_parallel.py); this leg records REAL chip
timings so the auto threshold (ops/moe.py DENSE_MASK_ELEMENT_LIMIT)
stops being folklore — the transcript lands in evidence/ via
tools/tpu_session.sh step 2."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel


def build(mode, n_tokens, e, hidden):
    cfg = FFConfig()
    cfg.batch_size = n_tokens
    cfg.moe_dispatch = mode
    ff = FFModel(cfg)
    x = ff.create_tensor((n_tokens, 64), name="input")
    t = ff.moe_ffn(x, num_experts=e, k=2, hidden_dim=hidden, name="moe")
    t = ff.dense(t, 10, name="head")
    ff.softmax(t)
    ff.compile(optimizer=AdamOptimizer(lr=1e-3),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def step_ms(ff, batch, steps=20):
    m = ff.train_batch(batch)
    float(m["loss"])  # device->host fetch delimits timing (tunnel)
    t0 = time.perf_counter()
    for _ in range(steps):
        m = ff.train_batch(batch)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps * 1e3


@pytest.mark.parametrize("e,n_tokens,hidden", [
    (8, 512, 512),      # 1.3M mask elements: BELOW the auto threshold
    (8, 4096, 512),     # 84M: just past it at small E
    (64, 8192, 512),    # 335M: large E, the sorted path's reason to be
])
def test_dispatch_ab_on_chip(e, n_tokens, hidden):
    rng = np.random.RandomState(0)
    batch = {"input": jnp.asarray(rng.randn(n_tokens, 64), jnp.float32),
             "label": jnp.asarray(rng.randint(0, 10, n_tokens),
                                  jnp.int32)}
    results = {}
    moe_op = None
    for mode in ("dense", "sorted"):
        ff = build(mode, n_tokens, e, hidden)
        moe_op = next(o for o in ff.ops if o.op_type == "moe_ffn")
        results[mode] = step_ms(ff, batch)
        l0 = float(ff.train_batch(batch)["loss"])
        assert np.isfinite(l0)
    # report what auto actually selects, via the REAL policy + the
    # op's real capacity (these timings exist to recalibrate
    # DENSE_MASK_ELEMENT_LIMIT — don't re-derive it by hand)
    from flexflow_tpu.ops.moe import use_sorted_dispatch

    class _AutoHolder:  # the loop's last model has moe_dispatch FORCED;
        config = FFConfig()  # the label must reflect the auto policy

    auto = use_sorted_dispatch(_AutoHolder(), n_tokens * moe_op.k, e,
                               moe_op.capacity, expert_sharded=False)
    print(f"\n[moe-dispatch A/B] E={e} tokens={n_tokens} "
          f"cap={moe_op.capacity}: "
          f"dense {results['dense']:.2f} ms  "
          f"sorted {results['sorted']:.2f} ms  "
          f"(auto picks {'sorted' if auto else 'dense'})")
    # both paths must run on chip; the printed timings calibrate the
    # threshold — no winner asserted (shape-dependent by design)
    assert results["dense"] > 0 and results["sorted"] > 0
