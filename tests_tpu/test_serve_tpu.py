"""Paged decode + ragged attention, COMPILED on-chip (the CPU suite
only ever runs the jnp fallback and the interpret-mode kernels;
Mosaic-compiled behavior is proven here), plus an end-to-end
ServeEngine generate with the Pallas serving path against the
CPU-identical jnp fallback tokens.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu.kernels.flash_attention import (
    _paged_decode_jnp,
    paged_attention_decode,
    paged_attention_ragged,
)


def _ragged(batch, seed, h=8, d=128, page_size=16, pages_per_seq=8):
    rng = np.random.RandomState(seed)
    num_pages = 1 + batch * pages_per_seq
    lens = rng.randint(1, pages_per_seq * page_size + 1, size=batch)
    kp = rng.randn(num_pages, page_size, h, d).astype(np.float32)
    vp = rng.randn(num_pages, page_size, h, d).astype(np.float32)
    table = np.zeros((batch, pages_per_seq), np.int32)
    pool = list(rng.permutation(np.arange(1, num_pages)))
    for b, L in enumerate(lens):
        for i in range(-(-int(L) // page_size)):
            table[b, i] = int(pool.pop())
    q = rng.randn(batch, h, d).astype(np.float32)
    return (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(lens.astype(np.int32)))


@pytest.mark.parametrize("batch", [1, 4, 8])
def test_paged_decode_mosaic_matches_jnp(batch):
    q, kp, vp, table, lens = _ragged(batch, batch)
    ref = _paged_decode_jnp(q, kp, vp, table, lens, scale=q.shape[-1] ** -0.5)
    out = jax.jit(lambda *a: paged_attention_decode(
        *a, use_pallas=True))(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("batch", [1, 4])
def test_paged_ragged_mosaic_matches_jnp(batch):
    """The mixed-step kernel (chunked prefill): several lanes per
    sequence at ragged positions, slot indirection in SMEM."""
    rng = np.random.RandomState(77 + batch)
    q1, kp, vp, table, lens = _ragged(batch, 7 + batch)
    h, d = q1.shape[1], q1.shape[2]
    slots, poss = [], []
    for s, L in enumerate(np.asarray(lens)):
        for p in sorted({int(L) - 1,
                         *(int(x) for x in rng.randint(0, int(L), 3))}):
            slots.append(s)
            poss.append(p)
    slots = jnp.asarray(np.asarray(slots, np.int32))
    lane_lens = jnp.asarray(np.asarray(poss, np.int32) + 1)
    q = jnp.asarray(rng.randn(len(poss), h, d).astype(np.float32))
    ref = paged_attention_ragged(q, kp, vp, table, slots, lane_lens,
                                 use_pallas=False)
    out = jax.jit(lambda *a: paged_attention_ragged(
        *a, use_pallas=True))(q, kp, vp, table, slots, lane_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_engine_pallas_decode_matches_jnp_tokens():
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.serve import ServeEngine

    cfg = FFConfig(batch_size=1, kv_page_size=16, kv_num_pages=65,
                   serve_max_seqs=4, serve_prefill_budget=64)
    ff = build_transformer_lm(cfg, vocab_size=128, max_seq_len=128,
                              hidden=128, num_heads=8, num_layers=2,
                              ff_dim=256)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 128, size=rng.randint(2, 40)))
               for _ in range(6)]
    eng_pl = ServeEngine(ff, use_pallas=True)
    eng_pl.warmup()
    out_pl = eng_pl.generate(prompts, 8)
    eng_jnp = ServeEngine(ff, use_pallas=False)
    out_jnp = eng_jnp.generate(prompts, 8)
    # greedy argmax over well-separated logits: kernel-order float
    # differences must not flip any token
    assert out_pl == out_jnp
