"""Pallas multi-timestep LSTM kernel COMPILED on-chip: numerics vs the
scan path at NMT shapes, plus timing — the measurement that decides
whether the kernel becomes the default (ops/rnn.py use_pallas tri-state).
Reference: nmt/lstm.cu, the cuDNN recurrence this replaces. Analysis:
under scan XLA re-reads wh (8 MB bf16 at H=1024) from HBM every
timestep — T=40 steps stream 320 MB for ~21 GFLOP; the kernel keeps wh
VMEM-resident."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels.lstm_scan import lstm_sequence, scan_reference


def make(T, B, H, dtype, seed=0):
    rng = np.random.RandomState(seed)
    xg = jnp.asarray(rng.randn(T, B, 4 * H) * 0.3, dtype)
    wh = jnp.asarray(rng.randn(H, 4 * H) * 0.05, dtype)
    h0 = jnp.zeros((B, H), dtype)
    c0 = jnp.zeros((B, H), dtype)
    return xg, wh, h0, c0


def timed(f, args, iters=10):
    y = jax.block_until_ready(f(*args))
    # block_until_ready handles pytrees, but through the axon tunnel a
    # device->host fetch is the only reliable sync — fetch the first leaf
    jnp.ravel(jax.tree_util.tree_leaves(y)[0])[0].item()
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(*args)
    jnp.ravel(jax.tree_util.tree_leaves(y)[0])[0].item()
    return (time.perf_counter() - t0) / iters


@pytest.mark.parametrize("dtype,atol", [(jnp.bfloat16, 5e-2),
                                        (jnp.float32, 1e-4)])
def test_lstm_kernel_compiled_matches_scan(dtype, atol):
    xg, wh, h0, c0 = make(T=40, B=64, H=1024, dtype=dtype)
    ys = jax.jit(lambda a, b, c, d: lstm_sequence(a, b, c, d))(
        xg, wh, h0, c0)
    want = scan_reference(xg, wh, h0, c0)
    err = np.max(np.abs(np.asarray(ys, np.float32)
                        - np.asarray(want, np.float32)))
    assert err < atol, err


@pytest.mark.xfail(strict=False, reason=(
    "informational timing: the committed dispatch default is the scan "
    "(LSTM use_pallas=None); this records the per-chip numbers that "
    "decide a flip (session step 7 A/Bs the same thing at bench "
    "scale). A slower kernel is a finding to act on, not a suite "
    "failure — the 2026-07-31 v5e run failed the old hard gate with "
    "its numbers lost to tail-truncation."))
def test_lstm_kernel_fwd_bwd_timing_vs_scan():
    xg, wh, h0, c0 = make(T=40, B=64, H=1024, dtype=jnp.bfloat16)

    def loss_k(xg, wh):
        return jnp.sum(lstm_sequence(xg, wh, h0, c0).astype(jnp.float32))

    def loss_s(xg, wh):
        return jnp.sum(scan_reference(xg, wh, h0, c0).astype(jnp.float32))

    t_kf = timed(jax.jit(lambda a, b: lstm_sequence(a, b, h0, c0)),
                 (xg, wh))
    t_sf = timed(jax.jit(lambda a, b: scan_reference(a, b, h0, c0)),
                 (xg, wh))
    t_kb = timed(jax.jit(jax.grad(loss_k, argnums=(0, 1))), (xg, wh))
    t_sb = timed(jax.jit(jax.grad(loss_s, argnums=(0, 1))), (xg, wh))
    print(f"\nLSTM recurrence T=40 B=64 H=1024 bf16: "
          f"fwd pallas {t_kf*1e6:.0f}us scan {t_sf*1e6:.0f}us | "
          f"fwd+bwd pallas {t_kb*1e6:.0f}us scan {t_sb*1e6:.0f}us")
    # the kernel must at minimum not be drastically slower; record the
    # numbers above for the dispatch decision (flip use_pallas auto when
    # consistently faster)
    assert t_kf < t_sf * 1.5, (t_kf, t_sf)
