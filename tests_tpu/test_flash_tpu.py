"""Pallas flash attention, COMPILED on-chip (VERDICT round-1 weak #2:
every CPU test runs interpret=True; Mosaic-compiled behavior is proven
here). Reference: the cuDNN fused-MHA op this kernel replaces,
src/ops/attention.cu:245.

Numerics: fwd + grads vs the XLA attention path at bench shapes, bf16
tolerances. Perf guard: at the shapes the dispatch heuristic sends to
flash (d=128, s>=1024 — measured sweep in ops/attention.py), the kernel
must not be slower than XLA beyond tunnel noise.
"""

import functools
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def xla_attn(q, k, v, causal):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    if causal:
        lq, lk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), dtype=bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def qkv(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)  # noqa
    return mk(), mk(), mk()


def timed(f, args, iters=10):
    y = f(*args)
    jnp.ravel(y)[0].item()  # device->host fetch drains the tunnel queue
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(*args)
    jnp.ravel(y)[0].item()
    return (time.perf_counter() - t0) / iters


@pytest.mark.parametrize("seq,d", [(512, 64), (1024, 64), (1024, 128)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_and_grads_compiled(seq, d, causal):
    from flexflow_tpu.kernels.flash_attention import flash_attention_bshd

    q, k, v = qkv(4, seq, 8, d)
    fl = jax.jit(functools.partial(flash_attention_bshd, causal=causal))
    xl = jax.jit(functools.partial(xla_attn, causal=causal))

    o_f = fl(q, k, v)
    o_x = xl(q, k, v)
    err = jnp.max(jnp.abs(o_f.astype(jnp.float32) - o_x.astype(jnp.float32)))
    assert float(err) < 0.05, float(err)  # bf16 accumulation tolerance

    def loss(fn):
        return jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32)),
            argnums=(0, 1, 2)))

    gf = loss(fl)(q, k, v)
    gx = loss(xl)(q, k, v)
    for a, b, name in zip(gf, gx, ("dq", "dk", "dv")):
        gerr = jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
        assert float(gerr) < 0.06, (name, float(gerr))


def test_flash_not_slower_where_dispatched():
    """At d=128, s=1024, causal — a shape the auto-heuristic routes to
    flash — the measured sweep saw flash 4.3ms vs XLA 5.2ms fwd. Guard
    with 1.4x headroom for tunnel timing noise."""
    from flexflow_tpu.kernels.flash_attention import flash_attention_bshd

    q, k, v = qkv(8, 1024, 8, 128)
    t_f = timed(jax.jit(functools.partial(flash_attention_bshd,
                                          causal=True)), (q, k, v))
    t_x = timed(jax.jit(functools.partial(xla_attn, causal=True)),
                (q, k, v))
    assert t_f < t_x * 1.4, (t_f, t_x)


@pytest.mark.parametrize("use_flash,b,seq,d,expect_flash", [
    (None, 2, 1024, 128, True),    # auto: eligible shape -> flash
    (None, 2, 256, 64, False),     # auto: XLA-favored shape -> no flash
    (True, 2, 256, 64, True),      # explicit True overrides the heuristic
    (False, 2, 1024, 128, False),  # explicit False always wins
])
def test_attention_op_dispatch_tristate(monkeypatch, use_flash, b, seq, d,
                                        expect_flash):
    """ADVICE round-1 #4: use_flash is tri-state — None=auto (measured
    heuristic), True=force the kernel, False=never. Verified by spying
    on the kernel entry point through the op's real dispatch."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.kernels import flash_attention as fa
    from flexflow_tpu.op import OpContext

    calls = []
    real = fa.flash_attention_bshd

    def spy(*args, **kw):
        calls.append(1)
        return real(*args, **kw)

    monkeypatch.setattr(fa, "flash_attention_bshd", spy)

    h = 8
    ff = FFModel(FFConfig())
    x = ff.create_tensor((b, seq, h * d), dtype=jnp.bfloat16, name="x")
    ff.multihead_attention(x, x, x, h * d, h, causal=True,
                           use_flash=use_flash, name="mha")
    op = ff.ops[0]
    rng = np.random.RandomState(0)
    qkv_in = jnp.asarray(rng.randn(b, seq, h * d), jnp.bfloat16)
    params = {n: jnp.zeros(s.shape, jnp.bfloat16)
              for n, s in op.weight_specs().items()}
    op.forward(params, [qkv_in] * 3, OpContext(training=False))
    assert bool(calls) == expect_flash, (calls, expect_flash)
