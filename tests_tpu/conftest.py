"""Hardware-gated tests: run ONLY on a real TPU (the ambient axon/TPU
platform of the bench image). The main suite under tests/ forces an
8-device CPU mesh; this directory is the on-chip complement — Pallas
kernels compiled by Mosaic, calibration microbenchmarks, sim-vs-real
validation (reference analog: the CI legs that needed real GPUs,
.circleci/config.yml / tests/multi_gpu_tests.sh).

Run manually: `python -m pytest tests_tpu/ -q` from the repo root with
the TPU tunnel up. Everything skips cleanly off-TPU.
"""

import pytest


import os

_HERE = os.path.dirname(os.path.abspath(__file__))


def pytest_collection_modifyitems(config, items):
    # session-scoped hook: only gate items that live in THIS directory
    # (a mixed `pytest tests/ tests_tpu/` run must not skip tests/).
    # fspath exists across pytest versions; the trailing separator stops
    # a sibling tests_tpu_* dir from matching.
    prefix = _HERE + os.path.sep
    ours = [it for it in items if str(it.fspath).startswith(prefix)]
    if not ours:
        return
    import jax
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    if not on_tpu:
        skip = pytest.mark.skip(reason="requires a real TPU backend")
        for item in ours:
            item.add_marker(skip)
