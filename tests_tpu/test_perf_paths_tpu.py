"""On-chip checks of the training-loop performance paths (the CPU suite
proves numerics; this proves them compiled for the real TPU backend):

- sparse embedding updates at DLRM-ish scale, vs the dense path;
- NHWC conv compute layout vs NCHW;
- the scanned multi-step dispatch vs sequential single steps;
- sibling-conv batching + NHWC layout residency vs the plain walk
  (round-5 conv paths) on an Inception-style module.

Reference analog: the real-GPU CI legs (tests/multi_gpu_tests.sh).
"""

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer


def _dlrm_like(sparse: bool, vocab=200000):
    cfg = FFConfig()
    cfg.batch_size = 64
    cfg.sparse_embedding_updates = sparse
    ff = FFModel(cfg)
    idx = ff.create_tensor((64, 1), dtype=np.int32, name="input")
    t = ff.embedding(idx, vocab, 64, aggr="sum")
    t = ff.dense(t, 32, activation="relu")
    t = ff.dense(t, 4)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    return ff


def test_sparse_update_matches_dense_on_chip():
    rng = np.random.RandomState(0)
    batches = [{"input": rng.randint(0, 200000, (64, 1)).astype(np.int32),
                "label": rng.randint(0, 4, (64,)).astype(np.int32)}
               for _ in range(3)]
    fs, fd = _dlrm_like(True), _dlrm_like(False)
    assert fs.executor._sparse_table_ops()
    for b in batches:
        ls = float(fs.train_batch(b)["loss"])
        ld = float(fd.train_batch(b)["loss"])
        np.testing.assert_allclose(ls, ld, rtol=1e-5)
    # spot-check the touched rows landed identically
    touched = np.unique(np.concatenate([b["input"].ravel()
                                        for b in batches]))
    emb = next(op.name for op in fs.ops if op.op_type == "embedding")
    ws = fs.get_weights(emb)["kernel"][touched]
    wd = fd.get_weights(emb)["kernel"][touched]
    np.testing.assert_allclose(ws, wd, rtol=1e-4, atol=1e-6)


def test_nhwc_matches_nchw_on_chip():
    def build(layout):
        cfg = FFConfig()
        cfg.batch_size = 16
        cfg.conv_layout = layout
        ff = FFModel(cfg)
        x = ff.create_tensor((16, 3, 32, 32), name="input")
        t = ff.conv2d(x, 32, 3, 3, 1, 1, 1, 1, activation="relu")
        t = ff.batch_norm(t, relu=True)
        t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
        t = ff.flat(t)
        t = ff.dense(t, 10)
        ff.softmax(t)
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    rng = np.random.RandomState(1)
    b = {"input": rng.randn(16, 3, 32, 32).astype(np.float32),
         "label": rng.randint(0, 10, (16,)).astype(np.int32)}
    a, c = build("NCHW"), build("NHWC")
    for _ in range(3):
        la = float(a.train_batch(b)["loss"])
        lc = float(c.train_batch(b)["loss"])
        np.testing.assert_allclose(la, lc, rtol=5e-4)


def test_multi_step_dispatch_on_chip():
    def build():
        cfg = FFConfig()
        cfg.batch_size = 32
        ff = FFModel(cfg)
        x = ff.create_tensor((32, 64), name="input")
        t = ff.dense(x, 128, activation="relu")
        t = ff.dense(t, 8)
        ff.softmax(t)
        ff.compile(optimizer=AdamOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    rng = np.random.RandomState(2)
    batches = [{"input": rng.randn(32, 64).astype(np.float32),
                "label": rng.randint(0, 8, (32,)).astype(np.int32)}
               for _ in range(6)]
    import jax
    seq, grp = build(), build()
    want = [float(seq.train_batch(b)["loss"]) for b in batches]
    got = list(np.asarray(jax.device_get(
        grp.train_batches(batches)["loss"]), np.float64))
    np.testing.assert_allclose(want, got, rtol=1e-5)


def test_sibling_fusion_and_residency_on_chip():
    """Round-5 conv paths compiled by the REAL backend: sibling-conv
    batching (merged 1x1 branch heads) and NHWC layout residency
    (values channels-last between conv-family ops, concat remapped to
    the channel axis) must match the plain NCHW unfused walk on an
    Inception-style module."""
    def build(fuse, layout):
        cfg = FFConfig()
        cfg.batch_size = 16
        cfg.sibling_conv_fusion = fuse
        cfg.conv_layout = layout
        ff = FFModel(cfg)
        x = ff.create_tensor((16, 16, 16, 16), name="input")
        b1 = ff.conv2d(x, 24, 1, 1, 1, 1, 0, 0, activation="relu")
        b2 = ff.conv2d(x, 12, 1, 1, 1, 1, 0, 0, activation="relu")
        b3 = ff.conv2d(x, 16, 1, 1, 1, 1, 0, 0, activation="relu")
        b3 = ff.conv2d(b3, 16, 3, 3, 1, 1, 1, 1, activation="relu")
        p = ff.pool2d(x, 3, 3, 1, 1, 1, 1)
        b4 = ff.conv2d(p, 8, 1, 1, 1, 1, 0, 0, activation="relu")
        t = ff.concat([b1, b2, b3, b4], axis=1)
        t = ff.batch_norm(t, relu=True)
        t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
        ff.softmax(ff.dense(ff.flat(t), 10))
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        return ff

    rng = np.random.RandomState(2)
    b = {"input": rng.randn(16, 16, 16, 16).astype(np.float32),
         "label": rng.randint(0, 10, (16,)).astype(np.int32)}
    ref = build(False, "NCHW")
    fused = build(True, "NCHW")
    resident = build(True, "NHWC")
    assert fused.executor._conv_merge_leader
    assert resident.executor._nhwc_resident
    for _ in range(3):
        lr_ = float(ref.train_batch(b)["loss"])
        lf = float(fused.train_batch(b)["loss"])
        ln = float(resident.train_batch(b)["loss"])
        np.testing.assert_allclose(lf, lr_, rtol=5e-4)
        np.testing.assert_allclose(ln, lr_, rtol=5e-4)
