"""Measured-cost grounding of the search (VERDICT round-1 missing #1 /
next-step #3). Reference: every simulated cost grounded in real on-device
kernel timings — inner_measure_operator_cost (src/runtime/model.cu:20-62),
per-(op,pc) cache (simulator.cc:301-321); the MLSys'19 claim is simulator
error < 30% (BASELINE.md).

On TPU: microbenchmarks measure the machine model's efficiency factors
(MXU fraction, HBM fraction, per-step dispatch overhead) once per device
kind; compile(search_budget>0) then never runs on the hard-coded
0.55/0.8 guesses.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu import models as zoo
from flexflow_tpu.search import measure
from flexflow_tpu.search.machine_model import TPUMachineModel


@pytest.fixture(scope="module")
def calibrated():
    return measure.calibrated_machine_model(force=True)


def test_factors_measured_not_guessed(calibrated):
    """Calibration must overwrite the analytic defaults with plausible
    measured fractions and persist them for future searches."""
    import os

    eff = calibrated.efficiency
    assert 0.2 < eff["matmul"] <= 1.0, eff
    assert 0.05 < eff["conv"] <= 1.0, eff  # conv-specific (VERDICT r2 #3)
    assert 0.2 < eff["elementwise"] <= 1.0, eff
    assert 0.0 < eff["step_overhead_s"] < 0.1, eff
    import jax
    path = measure.calibration_cache_path(jax.devices()[0].device_kind)
    assert os.path.exists(path)


def test_search_machine_model_uses_calibration(calibrated):
    """The search path's machine model (mcmc.optimize ->
    calibrated_machine_model) must carry the measured factors, not the
    dataclass defaults."""
    mm2 = measure.calibrated_machine_model()  # memoized path
    assert mm2.efficiency["matmul"] == calibrated.efficiency["matmul"]
    defaults = TPUMachineModel.__dataclass_fields__[
        "efficiency"].default_factory()
    assert mm2.efficiency["matmul"] != defaults["matmul"]


@pytest.mark.parametrize("batch,seq,layers,envelope", [
    (16, 256, 4, 0.35),   # small config: observed -12% (2026-07)
    (32, 512, 6, 0.30),   # flagship bench config: observed -25%
])
def test_sim_vs_real_within_envelope(calibrated, batch, seq, layers,
                                     envelope):
    """Pre-calibration simulator prediction vs a real measured training
    step — the MLSys'19 <30% envelope, checked on the bench transformer.
    (calibrate_simulator also sets the end-to-end scale afterwards, which
    future simulate() calls inherit.)"""
    cfg = FFConfig()
    cfg.batch_size = batch
    ff = zoo.build_transformer(cfg, batch_size=batch, seq_len=seq,
                               hidden=512, num_heads=8, num_layers=layers,
                               ff_dim=2048, num_classes=10,
                               dtype=jnp.bfloat16)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy", metrics=[])
    rng = np.random.RandomState(0)
    data = {"input": jnp.asarray(rng.randn(batch, seq, 512), jnp.bfloat16),
            "label": jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)}
    measured, predicted = ff.calibrate_simulator(batch=data, steps=20)
    err = abs(predicted - measured) / measured
    assert err < envelope, (measured, predicted, err)
    # after end-to-end calibration the same strategy must predict exactly
    from flexflow_tpu.parallel.pconfig import Strategy
    scaled = ff.simulator.simulate(ff.strategy or Strategy())
    assert abs(scaled - measured) / measured < 0.02, (scaled, measured)


def test_measured_grounding_tightens_the_envelope():
    """--measure-ops grounding (VERDICT r3 #6, round 4): per-op
    measured costs must predict the real step at least as well as the
    analytic roofline on the bench transformer config."""
    def predict(measure_n):
        cfg = FFConfig()
        cfg.batch_size = 16
        cfg.measure_top_ops = measure_n
        ff = zoo.build_transformer(cfg, batch_size=16, seq_len=256,
                                   hidden=512, num_heads=8,
                                   num_layers=4, ff_dim=2048,
                                   num_classes=10, dtype=jnp.bfloat16)
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy",
                   metrics=[])
        rng = np.random.RandomState(0)
        data = {"input": jnp.asarray(rng.randn(16, 256, 512),
                                     jnp.bfloat16),
                "label": jnp.asarray(rng.randint(0, 10, (16,)),
                                     jnp.int32)}
        measured, predicted = ff.calibrate_simulator(batch=data,
                                                     steps=10)
        return abs(predicted - measured) / measured

    err_analytic = predict(0)
    err_grounded = predict(8)
    # grounded must be in the envelope and not meaningfully worse than
    # analytic (on-chip the roofline is already decent; grounding must
    # never regress it)
    assert err_grounded < max(0.30, err_analytic * 1.2), (
        err_analytic, err_grounded)
