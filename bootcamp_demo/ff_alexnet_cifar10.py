"""Bootcamp demo: AlexNet on CIFAR-10 (reference:
bootcamp_demo/ff_alexnet_cifar10.py — the end-to-end walkthrough script
with per-epoch throughput/accuracy prints).

  python -m flexflow_tpu bootcamp_demo/ff_alexnet_cifar10.py -e 2
"""

import sys

import numpy as np

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.frontends.keras import datasets
from flexflow_tpu.models import build_alexnet


def top_level_task():
    cfg = FFConfig.from_args()
    n = 2048
    if "--samples" in sys.argv:
        n = int(sys.argv[sys.argv.index("--samples") + 1])

    # real cached CIFAR-10 when present, synthetic with exact shapes
    # otherwise (the reference's synthetic-input fallback)
    (x_train, y_train), _ = datasets.cifar10.load_data()
    x = np.transpose(x_train[:n], (0, 3, 1, 2)).astype(np.float32) / 255.0
    y = y_train[:n].reshape(-1).astype(np.int32)

    ff = build_alexnet(cfg, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    print(ff.summary())

    hist = ff.fit({"input": x}, y, epochs=cfg.epochs)
    print(f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
