"""Pure-torch MLP baseline, module-class variant (reference:
examples/python/pytorch/mnist_mlp_torch2.py — same network as
mnist_mlp.py trained directly in torch, for loss-trajectory
comparison against the framework import path).

  python examples/python/pytorch/mnist_mlp_torch2.py -e 1
"""

import sys

import numpy as np
import torch
import torch.nn as nn


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 512)
        self.fc2 = nn.Linear(512, 512)
        self.fc3 = nn.Linear(512, 10)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.fc1(x))
        x = self.relu(self.fc2(x))
        return self.fc3(x)


def main():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 64
    torch.manual_seed(0)
    model = MLP()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    loss_fn = nn.CrossEntropyLoss()

    rng = np.random.RandomState(0)
    x_np = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y_np = np.argmax(x_np @ w, axis=1).astype(np.int64)
    x, y = torch.from_numpy(x_np), torch.from_numpy(y_np)

    for epoch in range(epochs):
        total, correct = 0.0, 0
        for i in range(0, len(x), bs):
            opt.zero_grad()
            logits = model(x[i:i + bs])
            loss = loss_fn(logits, y[i:i + bs])
            loss.backward()
            opt.step()
            total += float(loss) * len(logits)
            correct += int((logits.argmax(-1) == y[i:i + bs]).sum())
        print(f"epoch {epoch}: loss={total / len(x):.4f} "
              f"acc={correct / len(x):.4f}")


if __name__ == "__main__":
    main()
