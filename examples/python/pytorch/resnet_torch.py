"""Pure-torch ResNet-18 training baseline (reference:
examples/python/pytorch/resnet_torch.py — the torch-only twin of
resnet.py, used to compare loss trajectories between the framework
and native torch on the same architecture).

  python examples/python/pytorch/resnet_torch.py -e 1
"""

import os
import sys

import numpy as np
import torch
import torch.nn as nn

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from resnet_defs import resnet18  # noqa: E402


def main():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 16
    torch.manual_seed(0)
    model = resnet18(num_classes=10, image_size=32)
    opt = torch.optim.SGD(model.parameters(), lr=0.01)
    loss_fn = nn.NLLLoss()

    rng = np.random.RandomState(0)
    n = int(os.environ.get("SAMPLES", 64))
    x = torch.from_numpy(rng.randn(n, 3, 32, 32).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, (n,)).astype(np.int64))

    for epoch in range(epochs):
        total = 0.0
        for i in range(0, n, bs):
            opt.zero_grad()
            probs = model(x[i:i + bs])
            loss = loss_fn(torch.log(probs + 1e-8), y[i:i + bs])
            loss.backward()
            opt.step()
            total += float(loss) * min(bs, n - i)
        print(f"epoch {epoch}: loss={total / n:.4f}")


if __name__ == "__main__":
    main()
