"""Train an fx-exported CIFAR-10 CNN graph file (reference:
examples/python/pytorch/cifar10_cnn.py — loads cnn.ff and trains; the
export half is cifar10_cnn_torch.py. Exports in-process when no path
is given).

  python examples/python/pytorch/cifar10_cnn.py [cnn.ff] -e 1
"""

import os
import sys
import tempfile

import numpy as np
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff


def make_cnn():
    return nn.Sequential(
        nn.Conv2d(3, 32, 3, 1, 1), nn.ReLU(),
        nn.Conv2d(32, 32, 3, 1, 1), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        nn.Conv2d(32, 64, 3, 1, 1), nn.ReLU(),
        nn.Conv2d(64, 64, 3, 1, 1), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        nn.Flatten(),
        nn.Linear(64 * 8 * 8, 512), nn.ReLU(),
        nn.Linear(512, 10), nn.Softmax(dim=-1))


def top_level_task():
    args = [a for a in sys.argv[1:] if a.endswith(".ff")]
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 16

    td = None
    if args:
        path = args[0]
    else:
        td = tempfile.TemporaryDirectory()
        path = os.path.join(td.name, "cnn.ff")
        export_ff(make_cnn(), path)
    ptm = PyTorchModel(path)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 3, 32, 32), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    n = int(os.environ.get("SAMPLES", 64))
    x = rng.randn(n, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)
    if td is not None:
        td.cleanup()


if __name__ == "__main__":
    top_level_task()
