"""Pure-torch ResNet-152 training (reference:
examples/python/pytorch/resnet152_training.py — torchvision's
resnet152 trained single-process; here the architecture is built
in-tree since torchvision is not a dependency, and shapes are kept
small so the script is a runnable smoke rather than an ImageNet run).

  python examples/python/pytorch/resnet152_training.py -e 1
"""

import os
import sys

import numpy as np
import torch
import torch.nn as nn

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from resnet_defs import resnet152  # noqa: E402


def main():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = int(os.environ.get("BATCH", 4))
    n = int(os.environ.get("SAMPLES", 8))
    width = int(os.environ.get("WIDTH", 16))  # 64 = the real model

    torch.manual_seed(0)
    model = resnet152(num_classes=10, image_size=32, width=width)
    opt = torch.optim.SGD(model.parameters(), lr=0.01, momentum=0.9)
    loss_fn = nn.NLLLoss()

    rng = np.random.RandomState(0)
    x = torch.from_numpy(rng.randn(n, 3, 32, 32).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, (n,)).astype(np.int64))

    for epoch in range(epochs):
        total = 0.0
        for i in range(0, n, bs):
            opt.zero_grad()
            probs = model(x[i:i + bs])
            loss = loss_fn(torch.log(probs + 1e-8), y[i:i + bs])
            loss.backward()
            opt.step()
            total += float(loss) * min(bs, n - i)
        print(f"epoch {epoch}: loss={total / n:.4f}")


if __name__ == "__main__":
    main()
