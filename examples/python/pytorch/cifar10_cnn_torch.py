"""PyTorch-frontend CIFAR-10 CNN with a residual add (reference:
examples/python/pytorch/cifar10_cnn_torch.py — torch.fx trace, export
.ff, replay + train).

  python examples/python/pytorch/cifar10_cnn_torch.py -e 1
"""

import os
import sys
import tempfile

import numpy as np
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff


class CNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 32, 3, padding=1)
        self.relu1 = nn.ReLU()
        self.conv2 = nn.Conv2d(32, 32, 3, padding=1)
        self.relu2 = nn.ReLU()
        self.pool = nn.MaxPool2d(2)
        self.flat = nn.Flatten()
        self.fc1 = nn.Linear(32 * 16 * 16, 256)
        self.relu3 = nn.ReLU()
        self.fc2 = nn.Linear(256, 10)
        self.sm = nn.Softmax(dim=-1)

    def forward(self, x):
        a = self.relu1(self.conv1(x))
        b = self.relu2(self.conv2(a))
        t = a + b  # residual add traces to ElementBinary
        t = self.pool(t)
        t = self.relu3(self.fc1(self.flat(t)))
        return self.sm(self.fc2(t))


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    batch_size = 16

    module = CNN()
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "cifar10_cnn.ff")
        export_ff(module, path)  # graph-only .ff roundtrip check
        PyTorchModel(path)
    ptm = PyTorchModel(module)

    cfg = FFConfig.from_args()
    cfg.batch_size = batch_size
    ff = FFModel(cfg)
    inp = ff.create_tensor((batch_size, 3, 32, 32), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    ptm.import_weights(ff)  # start from the torch module's weights

    rng = np.random.RandomState(0)
    x = rng.randn(128, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, 128).astype(np.int32)
    hist = ff.fit({"input": x}, y, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
