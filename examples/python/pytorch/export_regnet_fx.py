"""Trace a RegNetX model to a .ff graph file (reference:
examples/python/pytorch/export_regnet_fx.py — classy_vision's
RegNetX32gf through flexflow.torch.fx; the in-tree RegNetX blocks
stand in, see regnet_defs.py).

  python examples/python/pytorch/export_regnet_fx.py [out.ff]
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
# runnable directly (no launcher): repo root for flexflow_tpu
sys.path.append(os.path.dirname(os.path.dirname(os.path.dirname(_here))))
from regnet_defs import regnet_x  # noqa: E402

from flexflow_tpu.frontends.torchfx import export_ff  # noqa: E402

out = sys.argv[1] if len(sys.argv) > 1 else "regnetx.ff"
export_ff(regnet_x(), out)
print(f"wrote {out}")
