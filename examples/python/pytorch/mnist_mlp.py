"""Train an fx-exported MLP graph file (reference:
examples/python/pytorch/mnist_mlp.py — the import half of the
round trip; mnist_mlp_torch.py is the export half. If no path is
given, the graph is exported in-process first).

  python examples/python/pytorch/mnist_mlp.py [mnist_mlp.ff] -e 1
"""

import os
import sys
import tempfile

import numpy as np
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff


def top_level_task():
    args = [a for a in sys.argv[1:] if a.endswith(".ff")]
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 64

    td = None
    if args:
        path = args[0]
    else:
        td = tempfile.TemporaryDirectory()
        path = os.path.join(td.name, "mnist_mlp.ff")
        export_ff(nn.Sequential(
            nn.Linear(784, 512), nn.ReLU(),
            nn.Linear(512, 512), nn.ReLU(),
            nn.Linear(512, 10), nn.Softmax(dim=-1)), path)
    ptm = PyTorchModel(path)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 784), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)
    if td is not None:
        td.cleanup()


if __name__ == "__main__":
    top_level_task()
