"""torch DistributedDataParallel ResNet-152 training (reference:
examples/python/pytorch/resnet152_DDP_training.py — the NCCL/DDP
baseline the reference compares its own data parallelism against; here
gloo over CPU processes so it runs anywhere).

  python examples/python/pytorch/resnet152_DDP_training.py -e 1
  WORLD=2 python examples/python/pytorch/resnet152_DDP_training.py
"""

import os
import sys

import numpy as np
import torch
import torch.distributed as dist
import torch.multiprocessing as mp
import torch.nn as nn
from torch.nn.parallel import DistributedDataParallel as DDP

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from resnet_defs import resnet152  # noqa: E402


def worker(rank, world, epochs):
    os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
    os.environ.setdefault("MASTER_PORT", "29541")
    dist.init_process_group("gloo", rank=rank, world_size=world)
    torch.manual_seed(0)
    width = int(os.environ.get("WIDTH", 16))  # 64 = the real model
    model = DDP(resnet152(num_classes=10, image_size=32, width=width))
    opt = torch.optim.SGD(model.parameters(), lr=0.01, momentum=0.9)
    loss_fn = nn.NLLLoss()

    bs, n = int(os.environ.get("BATCH", 4)), int(os.environ.get("SAMPLES", 8))
    rng = np.random.RandomState(rank)  # each rank its own shard
    x = torch.from_numpy(rng.randn(n, 3, 32, 32).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, (n,)).astype(np.int64))

    for epoch in range(epochs):
        total = 0.0
        for i in range(0, n, bs):
            opt.zero_grad()
            probs = model(x[i:i + bs])
            loss = loss_fn(torch.log(probs + 1e-8), y[i:i + bs])
            loss.backward()  # DDP all-reduces grads here
            opt.step()
            total += float(loss) * min(bs, n - i)
        if rank == 0:
            print(f"epoch {epoch}: loss={total / n:.4f} "
                  f"(world={world})")
    dist.destroy_process_group()


def main():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    world = int(os.environ.get("WORLD", 1))
    if world == 1:
        worker(0, 1, epochs)
    else:
        mp.spawn(worker, args=(world, epochs), nprocs=world, join=True)


if __name__ == "__main__":
    main()
