"""Plain-torch RegNetX blocks (reference:
examples/python/pytorch/export_regnet_fx.py pulls RegNetX32gf from
classy_vision; that package is not a dependency, so the X-block
architecture — 1x1 reduce, 3x3 grouped conv, 1x1 expand, residual —
is expressed here directly with the torchfx-importable layer set."""

import torch.nn as nn


class XBlock(nn.Module):
    def __init__(self, cin, cout, stride=1, group_width=8):
        super().__init__()
        groups = max(1, cout // group_width)
        self.a = nn.Sequential(
            nn.Conv2d(cin, cout, 1, bias=False),
            nn.BatchNorm2d(cout), nn.ReLU())
        self.b = nn.Sequential(
            nn.Conv2d(cout, cout, 3, stride, 1, groups=groups,
                      bias=False),
            nn.BatchNorm2d(cout), nn.ReLU())
        self.c = nn.Sequential(
            nn.Conv2d(cout, cout, 1, bias=False),
            nn.BatchNorm2d(cout))
        self.relu = nn.ReLU()
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        return self.relu(self.c(self.b(self.a(x))) + idt)


def regnet_x(widths=(32, 64, 128), depths=(1, 2, 2), num_classes=10,
             image_size=32, group_width=8):
    stem = [nn.Conv2d(3, widths[0], 3, 1, 1, bias=False),
            nn.BatchNorm2d(widths[0]), nn.ReLU()]
    blocks, cin = [], widths[0]
    for i, (w, d) in enumerate(zip(widths, depths)):
        for j in range(d):
            stride = 2 if (i > 0 and j == 0) else 1
            blocks.append(XBlock(cin, w, stride, group_width))
            cin = w
    final = image_size // (2 ** (len(widths) - 1))
    head = [nn.AvgPool2d(final), nn.Flatten(),
            nn.Linear(cin, num_classes), nn.Softmax(dim=-1)]
    return nn.Sequential(*(stem + blocks + head))
