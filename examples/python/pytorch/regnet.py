"""fx-import a RegNetX model and train it (reference:
examples/python/pytorch/regnet.py — load the .ff exported by
export_regnet_fx.py and train; grouped 3x3 convs exercise the
frontend's feature_group_count path).

  python examples/python/pytorch/regnet.py -e 1
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from regnet_defs import regnet_x  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer  # noqa: E402
from flexflow_tpu.frontends.torchfx import (PyTorchModel,  # noqa: E402
                                            export_ff)


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 16

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "regnetx.ff")
        export_ff(regnet_x(num_classes=10, image_size=32), path)
        ptm = PyTorchModel(path)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 3, 32, 32), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    n = int(os.environ.get("SAMPLES", 64))
    x = rng.randn(n, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
