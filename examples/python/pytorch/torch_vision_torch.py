"""Pure-torchvision training baseline (reference:
examples/python/pytorch/torch_vision_torch.py). Import-gated like
torch_vision.py.

  python examples/python/pytorch/torch_vision_torch.py -e 1
"""

import os
import sys

import numpy as np
import torch
import torch.nn as nn


def main():
    try:
        import torchvision.models as tvm
    except ImportError:
        print("torchvision not installed; skipping "
              "(pip install torchvision to run; "
              "examples/python/pytorch/resnet_torch.py is the "
              "in-tree equivalent)")
        return

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 8
    torch.manual_seed(0)
    model = tvm.resnet18(num_classes=10)
    opt = torch.optim.SGD(model.parameters(), lr=0.01)
    loss_fn = nn.CrossEntropyLoss()

    rng = np.random.RandomState(0)
    n = int(os.environ.get("SAMPLES", 16))
    x = torch.from_numpy(rng.randn(n, 3, 224, 224).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, (n,)).astype(np.int64))

    for epoch in range(epochs):
        total = 0.0
        for i in range(0, n, bs):
            opt.zero_grad()
            loss = loss_fn(model(x[i:i + bs]), y[i:i + bs])
            loss.backward()
            opt.step()
            total += float(loss) * min(bs, n - i)
        print(f"epoch {epoch}: loss={total / n:.4f}")


if __name__ == "__main__":
    main()
