"""PyTorch-frontend example (reference: examples/python/pytorch/mnist_mlp.py
— torch.fx-trace a torch module, export the .ff graph file, replay it
onto an FFModel and train).

  python examples/python/pytorch/mnist_mlp_torch.py -e 1
"""

import os
import sys
import tempfile

import numpy as np
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 512)
        self.relu1 = nn.ReLU()
        self.fc2 = nn.Linear(512, 10)
        self.sm = nn.Softmax(dim=-1)

    def forward(self, x):
        return self.sm(self.fc2(self.relu1(self.fc1(x))))


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    batch_size = 64

    # trace -> .ff file -> replay (the reference round-trip,
    # torch/fx.py + torch/model.py)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "mnist_mlp.ff")
        export_ff(MLP(), path)
        ptm = PyTorchModel(path)

    cfg = FFConfig.from_args()
    cfg.batch_size = batch_size
    ff = FFModel(cfg)
    inp = ff.create_tensor((batch_size, 784), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
