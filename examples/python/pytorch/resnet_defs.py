"""Plain-torch ResNet builders shared by the pytorch example scripts
(reference: examples/python/pytorch/resnet_torch.py defines its own
copy; torchvision is not assumed to be installed).

Standard He et al. architecture expressed with the layer set the
torchfx frontend understands (Conv2d / BatchNorm2d / ReLU / pools /
add / flatten / Linear)."""

import torch.nn as nn


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + idt)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return self.relu(y + idt)


def resnet(block, layers, num_classes=10, image_size=32, width=64):
    """Stack `layers` (e.g. [2,2,2,2] = resnet18, [3,8,36,3] =
    resnet152) of `block` into a sequential model ending in a fixed
    avg-pool + linear head (adaptive pooling is avoided so the graph
    traces into the frontends' fixed-shape op set)."""
    stem = [nn.Conv2d(3, width, 3, 1, 1, bias=False),
            nn.BatchNorm2d(width), nn.ReLU()]
    blocks, cin = [], width
    for i, n in enumerate(layers):
        w = width * (2 ** i)
        for j in range(n):
            stride = 2 if (i > 0 and j == 0) else 1
            blocks.append(block(cin, w, stride))
            cin = w * block.expansion
    final = image_size // (2 ** (len(layers) - 1))
    head = [nn.AvgPool2d(final), nn.Flatten(),
            nn.Linear(cin, num_classes), nn.Softmax(dim=-1)]
    return nn.Sequential(*(stem + blocks + head))


def resnet18(**kw):
    return resnet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet152(**kw):
    return resnet(Bottleneck, [3, 8, 36, 3], **kw)
