"""fx-import a torchvision model (reference:
examples/python/pytorch/torch_vision.py — torchvision.models through
the fx exporter). Import-gated: torchvision is not a dependency of
this image; without it the script prints a clear skip and exits 0.

  python examples/python/pytorch/torch_vision.py -e 1
"""

import os
import sys
import tempfile

import numpy as np


def top_level_task():
    try:
        import torchvision.models as tvm
    except ImportError:
        print("torchvision not installed; skipping "
              "(pip install torchvision to run; "
              "examples/python/pytorch/resnet.py is the in-tree "
              "equivalent)")
        return

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.frontends.torchfx import PyTorchModel, export_ff

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 8

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "tv_resnet18.ff")
        export_ff(tvm.resnet18(num_classes=10), path)
        ptm = PyTorchModel(path)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 3, 224, 224), name="input")
    ptm.apply(ff, [inp])
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    n = int(os.environ.get("SAMPLES", 16))
    x = rng.randn(n, 3, 224, 224).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
