"""keras_exp functional MLP with tower concat (reference:
examples/python/keras_exp/func_mnist_mlp_concat.py). Import-gated:
without tensorflow this prints a clear skip and exits 0.

  python examples/python/keras_exp/func_mnist_mlp_concat.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends.keras_exp import HAS_TF


def top_level_task():
    if not HAS_TF:
        print("tensorflow not installed; skipping "
              "(pip install tensorflow to run)")
        return

    from tensorflow import keras as tfk

    from flexflow_tpu.frontends.keras_exp import from_tf_keras

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = tfk.Input((784,), name="input")
    a = tfk.layers.Dense(256, activation="relu")(inp)
    b = tfk.layers.Dense(256, activation="relu")(inp)
    t = tfk.layers.Concatenate(axis=1)([a, b])
    out = tfk.layers.Dense(10, activation="softmax")(t)
    ff = from_tf_keras(tfk.Model(inp, out), batch_size=64)
    ff.compile(loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(512, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
