"""keras_exp-frontend example (reference:
examples/python/keras_exp/mnist_mlp.py — import a REAL tf.keras model
object). Import-gated: without tensorflow this prints a clear skip
message and exits 0.

  python examples/python/keras_exp/func_mnist_mlp_exp.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends.keras_exp import HAS_TF


def top_level_task():
    if not HAS_TF:
        print("tensorflow not installed; skipping "
              "(pip install tensorflow to run)")
        return

    from tensorflow import keras as tfk

    from flexflow_tpu.frontends.keras_exp import from_tf_keras

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = tfk.Input((784,))
    t = tfk.layers.Dense(256, activation="relu")(inp)
    out = tfk.layers.Dense(10, activation="softmax")(t)
    tf_model = tfk.Model(inp, out)

    ff = from_tf_keras(tf_model, batch_size=64)
    ff.compile(loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(512, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    hist = ff.fit({ff.input_tensors[0].name: x}, y, epochs=epochs)
    print(f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
