"""keras_exp functional CIFAR-10 CNN with branch concat (reference:
examples/python/keras_exp/func_cifar10_cnn_concat.py). Import-gated:
without tensorflow this prints a clear skip and exits 0.

  python examples/python/keras_exp/func_cifar10_cnn_concat.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends.keras_exp import HAS_TF


def top_level_task():
    if not HAS_TF:
        print("tensorflow not installed; skipping "
              "(pip install tensorflow to run)")
        return

    from tensorflow import keras as tfk

    from flexflow_tpu.frontends.keras_exp import from_tf_keras

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = tfk.Input((3, 32, 32), name="input")
    a = tfk.layers.Conv2D(32, 3, padding="same", activation="relu",
                          data_format="channels_first")(inp)
    b = tfk.layers.Conv2D(32, 3, padding="same", activation="relu",
                          data_format="channels_first")(inp)
    t = tfk.layers.Concatenate(axis=1)([a, b])
    t = tfk.layers.MaxPooling2D(2, data_format="channels_first")(t)
    t = tfk.layers.Flatten()(t)
    out = tfk.layers.Dense(10, activation="softmax")(t)
    ff = from_tf_keras(tfk.Model(inp, out), batch_size=16)
    ff.compile(loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(64, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (64,)).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
