"""Export a torch AlexNet to .onnx for the importer example
(reference: examples/python/onnx/alexnet_pt.py — the export half;
onnx/alexnet.py trains the file. CIFAR-sized 32x32 input like the
in-tree native alexnet so the training half is a fast smoke).

  python examples/python/onnx/alexnet_pt.py [alexnet.onnx]
"""

import os
import sys

import torch
import torch.nn as nn

sys.path.append(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))


def make_alexnet(num_classes=10):
    return nn.Sequential(
        nn.Conv2d(3, 64, 5, 1, 2), nn.ReLU(), nn.MaxPool2d(2, 2),
        nn.Conv2d(64, 192, 3, 1, 1), nn.ReLU(), nn.MaxPool2d(2, 2),
        nn.Conv2d(192, 384, 3, 1, 1), nn.ReLU(),
        nn.Conv2d(384, 256, 3, 1, 1), nn.ReLU(),
        nn.Conv2d(256, 256, 3, 1, 1), nn.ReLU(), nn.MaxPool2d(2, 2),
        nn.Flatten(),
        nn.Linear(256 * 4 * 4, 1024), nn.ReLU(),
        nn.Linear(1024, 1024), nn.ReLU(),
        nn.Linear(1024, num_classes), nn.Softmax(dim=-1))


def main():
    from flexflow_tpu.frontends.onnx import export_torch_onnx
    out = sys.argv[1] if len(sys.argv) > 1 else "alexnet.onnx"
    export_torch_onnx(make_alexnet(), torch.randn(16, 3, 32, 32), out,
                      input_names=["input"])
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
