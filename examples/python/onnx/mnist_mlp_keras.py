"""Export a tf.keras MNIST MLP to .onnx and train it (reference:
examples/python/onnx/mnist_mlp_keras.py — keras2onnx export). Gated:
tensorflow is not a dependency of this image; without it the script
prints a clear skip and exits 0 (mnist_mlp_pt.py is the torch-export
equivalent that always runs).

  python examples/python/onnx/mnist_mlp_keras.py -e 1
"""

import sys


def top_level_task():
    try:
        import tensorflow as tf  # noqa: F401
        import tf2onnx  # noqa: F401
    except ImportError:
        print("tensorflow/tf2onnx not installed; skipping "
              "(examples/python/onnx/mnist_mlp_pt.py is the "
              "torch-export equivalent)")
        return

    import tempfile

    import numpy as np
    from tensorflow import keras as tfk

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.frontends.onnx import ONNXModel

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 64

    model = tfk.Sequential([
        tfk.layers.Dense(512, activation="relu", input_shape=(784,)),
        tfk.layers.Dense(512, activation="relu"),
        tfk.layers.Dense(10, activation="softmax")])
    spec = (tf.TensorSpec((bs, 784), tf.float32, name="input"),)
    with tempfile.NamedTemporaryFile(suffix=".onnx") as f:
        import tf2onnx.convert
        tf2onnx.convert.from_keras(model, input_signature=spec,
                                   output_path=f.name)
        om = ONNXModel(f.name)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 784), name="input")
    om.apply(ff, {"input": inp})
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
