"""Expected-accuracy floors for the onnx example zoo (reference:
examples/python/onnx/accuracy.py — an enum of per-model accuracy
floors the CI accuracy tests assert against)."""

from enum import Enum


class ModelAccuracy(Enum):
    MNIST_MLP = 90.0
    MNIST_CNN = 98.0
    CIFAR10_CNN = 78.0
    CIFAR10_ALEXNET = 71.0
