"""Export a torch CIFAR-10 CNN to .onnx (reference:
examples/python/onnx/cifar10_cnn_pt.py; onnx/cifar10_cnn.py trains
the exported file).

  python examples/python/onnx/cifar10_cnn_pt.py [cnn.onnx]
"""

import os
import sys

import torch
import torch.nn as nn

sys.path.append(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))


def make_cnn(num_classes=10):
    return nn.Sequential(
        nn.Conv2d(3, 32, 3, 1, 1), nn.ReLU(),
        nn.Conv2d(32, 32, 3, 1, 1), nn.ReLU(), nn.MaxPool2d(2, 2),
        nn.Conv2d(32, 64, 3, 1, 1), nn.ReLU(),
        nn.Conv2d(64, 64, 3, 1, 1), nn.ReLU(), nn.MaxPool2d(2, 2),
        nn.Flatten(),
        nn.Linear(64 * 8 * 8, 512), nn.ReLU(),
        nn.Linear(512, num_classes), nn.Softmax(dim=-1))


def main():
    from flexflow_tpu.frontends.onnx import export_torch_onnx
    out = sys.argv[1] if len(sys.argv) > 1 else "cifar10_cnn.onnx"
    export_torch_onnx(make_cnn(), torch.randn(16, 3, 32, 32), out,
                      input_names=["input"])
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
