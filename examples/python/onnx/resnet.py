"""Import the ResNet .onnx graph and train it (reference:
examples/python/onnx/resnet.py; export half is resnet_pt.py. Exports
in-process when no file is given).

  python examples/python/onnx/resnet.py [resnet.onnx] -e 1
"""

import os
import sys
import tempfile

import numpy as np
import torch

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.append(os.path.join(os.path.dirname(_here), "pytorch"))
from resnet_defs import resnet18  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer  # noqa: E402
from flexflow_tpu.frontends.onnx import (ONNXModel,  # noqa: E402
                                         export_torch_onnx)


def top_level_task():
    args = [a for a in sys.argv[1:] if a.endswith(".onnx")]
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 16

    if args:
        om = ONNXModel(args[0])
    else:
        with tempfile.NamedTemporaryFile(suffix=".onnx") as f:
            export_torch_onnx(resnet18(num_classes=10, image_size=32),
                              torch.randn(bs, 3, 32, 32), f.name,
                              input_names=["input"])
            om = ONNXModel(f.name)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 3, 32, 32), name="input")
    om.apply(ff, {"input": inp})
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    n = int(os.environ.get("SAMPLES", 64))
    x = rng.randn(n, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
