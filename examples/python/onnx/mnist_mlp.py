"""Import the MNIST MLP .onnx graph and train it (reference:
examples/python/onnx/mnist_mlp.py; export half is mnist_mlp_pt.py.
Exports in-process when no file is given; see also mnist_mlp_onnx.py,
the original in-tree round-trip demo).

  python examples/python/onnx/mnist_mlp.py [mnist_mlp.onnx] -e 1
"""

import os
import sys
import tempfile

import numpy as np
import torch

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mnist_mlp_pt import make_mlp  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer  # noqa: E402
from flexflow_tpu.frontends.onnx import (ONNXModel,  # noqa: E402
                                         export_torch_onnx)


def top_level_task():
    args = [a for a in sys.argv[1:] if a.endswith(".onnx")]
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 64

    if args:
        om = ONNXModel(args[0])
    else:
        with tempfile.NamedTemporaryFile(suffix=".onnx") as f:
            export_torch_onnx(make_mlp(), torch.randn(bs, 784), f.name,
                              input_names=["input"])
            om = ONNXModel(f.name)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 784), name="input")
    om.apply(ff, {"input": inp})
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
