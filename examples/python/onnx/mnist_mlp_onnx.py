"""ONNX-frontend example (reference: examples/python/onnx/mnist_mlp.py
— import an ONNX graph and train it). Runs with or without the `onnx`
package: a real `.onnx` file is exported via torch and read back
through the in-tree wire-format decoder (frontends/onnx_wire.py) when
`onnx` is absent.

  python examples/python/onnx/mnist_mlp_onnx.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer


def top_level_task():
    try:
        import torch
        import torch.nn as nn
    except ImportError:
        print("torch not installed; this example exports the test graph "
              "via torch.onnx (pip install torch to run)")
        return

    from flexflow_tpu.frontends.onnx import ONNXModel, export_torch_onnx

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 64

    module = nn.Sequential(nn.Linear(784, 256), nn.ReLU(),
                           nn.Linear(256, 10), nn.Softmax(dim=-1))
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".onnx") as f:
        export_torch_onnx(module, torch.randn(bs, 784), f.name,
                          input_names=["input"])
        om = ONNXModel(f.name)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    # input tensors straight from the graph's declared inputs
    om.apply(ff, om.make_input_tensors(ff, batch_size=bs))
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(512, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    hist = ff.fit({"input": x}, y, epochs=epochs)
    print(f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
