"""Export a torch ResNet-18 to .onnx (reference:
examples/python/onnx/resnet_pt.py; onnx/resnet.py trains the exported
file — residual Adds exercise the importer's elementwise path).

  python examples/python/onnx/resnet_pt.py [resnet.onnx]
"""

import os
import sys

import torch

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.append(os.path.join(os.path.dirname(_here), "pytorch"))
sys.path.append(os.path.dirname(os.path.dirname(os.path.dirname(_here))))
from resnet_defs import resnet18  # noqa: E402


def main():
    from flexflow_tpu.frontends.onnx import export_torch_onnx
    out = sys.argv[1] if len(sys.argv) > 1 else "resnet.onnx"
    export_torch_onnx(resnet18(num_classes=10, image_size=32),
                      torch.randn(16, 3, 32, 32), out,
                      input_names=["input"])
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
