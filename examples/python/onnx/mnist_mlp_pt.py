"""Export a torch MNIST MLP to .onnx (reference:
examples/python/onnx/mnist_mlp_pt.py; onnx/mnist_mlp.py trains the
exported file).

  python examples/python/onnx/mnist_mlp_pt.py [mnist_mlp.onnx]
"""

import os
import sys

import torch
import torch.nn as nn

sys.path.append(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))


def make_mlp(num_classes=10):
    return nn.Sequential(
        nn.Linear(784, 512), nn.ReLU(),
        nn.Linear(512, 512), nn.ReLU(),
        nn.Linear(512, num_classes), nn.Softmax(dim=-1))


def main():
    from flexflow_tpu.frontends.onnx import export_torch_onnx
    out = sys.argv[1] if len(sys.argv) > 1 else "mnist_mlp.onnx"
    export_torch_onnx(make_mlp(), torch.randn(64, 784), out,
                      input_names=["input"])
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
