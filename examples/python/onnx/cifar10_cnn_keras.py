"""Export a tf.keras CIFAR-10 CNN to .onnx and train it (reference:
examples/python/onnx/cifar10_cnn_keras.py). Gated like
mnist_mlp_keras.py: without tensorflow/tf2onnx this prints a clear
skip and exits 0 (cifar10_cnn_pt.py is the torch-export equivalent).

  python examples/python/onnx/cifar10_cnn_keras.py -e 1
"""

import sys


def top_level_task():
    try:
        import tensorflow as tf  # noqa: F401
        import tf2onnx  # noqa: F401
    except ImportError:
        print("tensorflow/tf2onnx not installed; skipping "
              "(examples/python/onnx/cifar10_cnn_pt.py is the "
              "torch-export equivalent)")
        return

    import tempfile

    import numpy as np
    from tensorflow import keras as tfk

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.frontends.onnx import ONNXModel

    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1
    bs = 16

    model = tfk.Sequential([
        tfk.layers.Conv2D(32, 3, padding="same", activation="relu",
                          input_shape=(3, 32, 32),
                          data_format="channels_first"),
        tfk.layers.Conv2D(32, 3, padding="same", activation="relu",
                          data_format="channels_first"),
        tfk.layers.MaxPooling2D(2, data_format="channels_first"),
        tfk.layers.Flatten(),
        tfk.layers.Dense(512, activation="relu"),
        tfk.layers.Dense(10, activation="softmax")])
    spec = (tf.TensorSpec((bs, 3, 32, 32), tf.float32, name="input"),)
    with tempfile.NamedTemporaryFile(suffix=".onnx") as f:
        import tf2onnx.convert
        tf2onnx.convert.from_keras(model, input_signature=spec,
                                   output_path=f.name)
        om = ONNXModel(f.name)

    cfg = FFConfig.from_args()
    cfg.batch_size = bs
    ff = FFModel(cfg)
    inp = ff.create_tensor((bs, 3, 32, 32), name="input")
    om.apply(ff, {"input": inp})
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(0)
    n = 64
    x = rng.randn(n, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int32)
    ff.fit({"input": x}, y, epochs=epochs)


if __name__ == "__main__":
    top_level_task()
