"""Keras Reshape layer example (reference:
examples/python/keras/reshape.py).

  python examples/python/keras/reshape.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = keras.layers.Input((784,))
    t = keras.layers.Reshape((1, 28, 28))(inp)
    t = keras.layers.Conv2D(16, (3, 3), activation="relu")(t)
    t = keras.layers.MaxPooling2D((2, 2))(t)
    t = keras.layers.Flatten()(t)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
