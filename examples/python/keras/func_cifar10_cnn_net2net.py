"""Net2Net on a functional CNN (reference:
examples/python/keras/func_cifar10_cnn_net2net.py;
tests/multi_gpu_tests.sh): widen a conv layer and seed the student's
filters from the teacher via host get/set weights.

  python examples/python/keras/func_cifar10_cnn_net2net.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def make(filters):
    inp = keras.layers.Input((3, 32, 32))
    t = keras.layers.Conv2D(filters, (3, 3), padding="same",
                            activation="relu", name="conv_w")(inp)
    t = keras.layers.MaxPooling2D((2, 2))(t)
    t = keras.layers.Flatten()(t)
    out = keras.layers.Dense(10, activation="softmax", name="head")(t)
    m = keras.Model(inputs=inp, outputs=out)
    m.compile(optimizer=keras.SGD(learning_rate=0.01),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    return m


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    rng = np.random.RandomState(0)
    x = rng.randn(256, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)

    teacher = make(16)
    teacher.fit(x, y, batch_size=32, epochs=epochs)

    student = make(32)   # widened conv: 16 -> 32 filters
    s_ff = student.build_model(batch_size=32)  # weights exist, untrained
    t_ff = teacher.ffmodel
    tw = t_ff.get_weights("conv_w")
    sw = {k: v.copy() for k, v in s_ff.get_weights("conv_w").items()}
    sw["kernel"][:16] = tw["kernel"]   # OIHW: copy teacher's filters
    sw["bias"][:16] = tw["bias"]
    s_ff.set_weights("conv_w", sw)

    hist = student.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
