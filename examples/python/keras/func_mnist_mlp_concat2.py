"""Functional-API MLP with chained Concatenates (reference:
examples/python/keras/func_mnist_mlp_concat2.py; tests/multi_gpu_tests.sh).

  python examples/python/keras/func_mnist_mlp_concat2.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = keras.layers.Input((784,))
    a = keras.layers.Dense(128, activation="relu")(inp)
    b = keras.layers.Dense(128, activation="tanh")(inp)
    c = keras.layers.Dense(128, activation="sigmoid")(inp)
    ab = keras.layers.Concatenate(axis=1)([a, b])
    abc = keras.layers.Concatenate(axis=1)([ab, c])
    t = keras.layers.Dense(64, activation="relu")(abc)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(512, 784).astype(np.float32)
    y = rng.randint(0, 10, 512).astype(np.int32)
    hist = model.fit(x, y, batch_size=64, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
