"""Reuters topic-classification MLP.

Reference: examples/python/keras/reuters_mlp.py — Embedding-free MLP over
multi-hot bag-of-words vectors, 46 classes. Runs on cached real data when
available, synthetic otherwise (see frontends/keras/datasets.py).

Usage: python examples/python/keras/reuters_mlp.py [-e EPOCHS]
"""

import argparse

import numpy as np

from flexflow_tpu.frontends import keras


def vectorize(seqs, dim):
    out = np.zeros((len(seqs), dim), np.float32)
    for i, s in enumerate(seqs):
        out[i, np.asarray(list(s), np.int64) % dim] = 1.0
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-e", "--epochs", type=int, default=2)
    ap.add_argument("--max-words", type=int, default=1000)
    ap.add_argument("-n", "--samples", type=int, default=2048)
    args, _ = ap.parse_known_args()

    (x_train, y_train), _ = keras.datasets.reuters.load_data(
        num_words=args.max_words)
    x_train = vectorize(x_train[:args.samples], args.max_words)
    y_train = np.asarray(y_train[:args.samples], np.int32)

    model = keras.Sequential([
        keras.layers.Dense(512, activation="relu",
                           input_shape=(args.max_words,)),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(46, activation="softmax"),
    ])
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    history = model.fit(x_train, y_train, batch_size=64,
                        epochs=args.epochs)
    print("final:", history[-1])


if __name__ == "__main__":
    main()
