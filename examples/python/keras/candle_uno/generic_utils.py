"""Small shared helpers for the candle_uno suite (reference role:
examples/python/keras/candle_uno/generic_utils.py)."""


def to_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def str2bool(v):
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("yes", "true", "t", "1")


class Struct:
    """Dot-access view over a parameter dict."""

    def __init__(self, **entries):
        self.__dict__.update(entries)
