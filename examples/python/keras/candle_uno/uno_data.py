"""Data provider for the candle_uno suite (reference role:
examples/python/keras/candle_uno/uno_data.py — CombinedDataLoader /
CombinedDataGenerator over the CANDLE drug-response CSVs). Offline by
design: synthetic cell-line/drug feature frames with a planted linear
response, so the model has real signal to fit without any downloads."""

import numpy as np

FEATURE_SHAPES = {
    "dose": 1,
    "cell.rnaseq": 64,
    "drug1.descriptors": 48,
}


class CombinedDataLoader:
    def __init__(self, seed=2018, samples=512):
        self.seed = seed
        self.samples = samples
        self.input_features = dict(FEATURE_SHAPES)

    def load(self):
        rng = np.random.RandomState(self.seed)
        n = self.samples
        self.x = {k: rng.randn(n, d).astype(np.float32)
                  for k, d in self.input_features.items()}
        # planted response: dose-weighted combination of a few feature
        # columns + noise, in [0, 1] like AUC
        raw = (self.x["dose"][:, 0]
               + 0.5 * self.x["cell.rnaseq"][:, :4].sum(axis=1)
               - 0.3 * self.x["drug1.descriptors"][:, :4].sum(axis=1))
        raw = raw + 0.05 * rng.randn(n).astype(np.float32)
        self.y = ((raw - raw.min()) / (np.ptp(raw) + 1e-9)) \
            .astype(np.float32).reshape(n, 1)
        return self


class CombinedDataGenerator:
    """Mini-batch iterator over a loaded CombinedDataLoader."""

    def __init__(self, loader, batch_size=64):
        self.loader = loader
        self.batch_size = batch_size

    def flow(self):
        n = len(self.loader.y)
        for i in range(0, n - self.batch_size + 1, self.batch_size):
            xs = [v[i:i + self.batch_size]
                  for v in self.loader.x.values()]
            yield xs, self.loader.y[i:i + self.batch_size]

    def get_slice(self):
        return list(self.loader.x.values()), self.loader.y
