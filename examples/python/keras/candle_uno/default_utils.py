"""CANDLE-style benchmark parameter infrastructure (reference role:
examples/python/keras/candle_uno/default_utils.py — Benchmark base
class + finalize_parameters merging file defaults, registered
additional definitions, and CLI flags into one param dict)."""

import argparse

from generic_utils import str2bool  # noqa: F401  (re-export, CANDLE API)

DEFAULTS = {
    "batch_size": 64,
    "epochs": 1,
    "learning_rate": 0.01,
    "dense": [256, 128],
    "dense_feature_layers": [64, 64],
    "activation": "relu",
    "residual": False,
    "optimizer": "sgd",
    "loss": "mse",
    "use_synthetic_data": True,
    "samples": 512,
}


class Benchmark:
    """Holds the parameter registry for one benchmark. Subclasses add
    entries via set_locals()."""

    def __init__(self, file_path, default_model, framework,
                 prog=None, desc=None):
        self.file_path = file_path
        self.default_model = default_model
        self.framework = framework
        self.prog = prog
        self.desc = desc
        self.required = set()
        self.additional_definitions = []
        self.set_locals()

    def set_locals(self):  # overridden per benchmark
        pass

    def parser(self):
        p = argparse.ArgumentParser(prog=self.prog,
                                    description=self.desc)
        for d in self.additional_definitions:
            name = "--" + d["name"].replace("_", "-")
            kw = {}
            if d.get("type") is bool:
                kw["type"] = str2bool
            elif d.get("type"):
                kw["type"] = d["type"]
            if "default" in d:
                kw["default"] = d["default"]
            if d.get("nargs"):
                kw["nargs"] = d["nargs"]
            if d.get("choices"):
                kw["choices"] = d["choices"]
            p.add_argument(name, help=d.get("help", ""), **kw)
        p.add_argument("-e", "--epochs", type=int)
        p.add_argument("-b", "--batch-size", type=int)
        return p


def finalize_parameters(bmk, argv=None):
    """DEFAULTS <- benchmark definitions <- CLI flags, left to right."""
    params = dict(DEFAULTS)
    for d in bmk.additional_definitions:
        if "default" in d:
            params[d["name"]] = d["default"]
    args, _ = bmk.parser().parse_known_args(argv)
    for k, v in vars(args).items():
        if v is not None:
            params[k] = v
    missing = bmk.required - set(params)
    if missing:
        raise ValueError(f"missing required params: {sorted(missing)}")
    return params
