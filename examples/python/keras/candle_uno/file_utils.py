"""File resolution for the candle_uno suite (reference role:
examples/python/keras/candle_uno/file_utils.py — download-and-cache
from the CANDLE data portal). This environment has no network egress,
so get_file resolves local paths and fails loudly on URLs instead of
silently hanging."""

import os


def get_file(fname, origin=None, cache_dir=None):
    """Return a local path for `fname`. A plain existing path passes
    through; a URL origin raises with a clear offline message."""
    if os.path.exists(fname):
        return fname
    cache_dir = cache_dir or os.path.join(
        os.path.expanduser("~"), ".candle_cache")
    cached = os.path.join(cache_dir, fname)
    if os.path.exists(cached):
        return cached
    if origin:
        raise RuntimeError(
            f"{fname} not cached and this environment has no network "
            f"egress (origin={origin}); place the file at {cached} or "
            f"run with synthetic data (use_synthetic_data=True, the "
            f"default in this suite)")
    raise FileNotFoundError(fname)
