"""Uno drug-response model on the Keras frontend (reference:
examples/python/keras/candle_uno/candle_uno.py — per-feature dense
towers concatenated into a regression trunk, parameters via the CANDLE
Benchmark machinery, data via uno_data).

  python examples/python/keras/candle_uno/candle_uno.py -e 1
"""

import os
import sys

file_path = os.path.dirname(os.path.realpath(__file__))
sys.path.insert(0, file_path)
sys.path.append(os.path.abspath(os.path.join(
    file_path, "..", "..", "..", "..")))

import numpy as np  # noqa: E402

import uno as benchmark  # noqa: E402
from default_utils import finalize_parameters  # noqa: E402
from generic_utils import to_list  # noqa: E402
from uno_data import CombinedDataGenerator, CombinedDataLoader  # noqa: E402

from flexflow_tpu.frontends import keras  # noqa: E402


def initialize_parameters(default_model="uno_default_model.txt"):
    bmk = benchmark.BenchmarkUno(
        benchmark.file_path, default_model, "keras", prog="uno_baseline",
        desc="Build neural network based models to predict tumor "
             "response to single and paired drugs.")
    return finalize_parameters(bmk)


def build_feature_model(input_shape, name="", dense_layers=(64, 64),
                        activation="relu", residual=False):
    x_input = keras.layers.Input(input_shape)
    h = x_input
    for width in to_list(dense_layers):
        x = h
        h = keras.layers.Dense(width, activation=activation)(h)
        if residual and x.shape[-1] == h.shape[-1]:
            h = keras.layers.Add()([h, x])
    return x_input, h


def build_model(params, loader):
    inputs, towers = [], []
    for fname, dim in loader.input_features.items():
        if dim <= 1:
            inp = keras.layers.Input((dim,))
            inputs.append(inp)
            towers.append(inp)
            continue
        inp, tower = build_feature_model(
            (dim,), name=fname,
            dense_layers=params["dense_feature_layers"],
            activation=params["activation"],
            residual=params["residual"])
        inputs.append(inp)
        towers.append(tower)
    t = keras.layers.Concatenate(axis=1)(towers)
    for width in to_list(params["dense"]):
        t = keras.layers.Dense(width,
                               activation=params["activation"])(t)
    out = keras.layers.Dense(1)(t)
    return keras.Model(inputs=inputs, outputs=out)


def run(params):
    loader = CombinedDataLoader(samples=params["samples"]).load()
    model = build_model(params, loader)
    model.compile(
        optimizer=keras.SGD(learning_rate=params["learning_rate"]),
        loss="mean_squared_error", metrics=["mse"])
    gen = CombinedDataGenerator(loader,
                                batch_size=params["batch_size"])
    xs, y = gen.get_slice()
    hist = model.fit(xs, y, batch_size=params["batch_size"],
                     epochs=params["epochs"])
    print(f"final loss: {hist[-1]['loss']:.4f}")
    return hist


def main():
    params = initialize_parameters()
    run(params)


if __name__ == "__main__":
    main()
