"""Uno benchmark definition (reference role:
examples/python/keras/candle_uno/uno.py — BenchmarkUno parameter spec
for the drug-response model)."""

import os
import sys

file_path = os.path.dirname(os.path.realpath(__file__))
sys.path.insert(0, file_path)

from default_utils import Benchmark  # noqa: E402

additional_definitions = [
    {"name": "agg_dose", "type": str, "default": None,
     "choices": ["AUC", "IC50", "EC50", "HS"],
     "help": "dose-independent response aggregation metric"},
    {"name": "cell_features", "nargs": "+", "default": ["rnaseq"],
     "choices": ["rnaseq", "none"],
     "help": "cell line feature set"},
    {"name": "drug_features", "nargs": "+", "default": ["descriptors"],
     "choices": ["descriptors", "none"],
     "help": "drug feature set"},
    {"name": "dense_feature_layers", "nargs": "+", "type": int,
     "default": [64, 64],
     "help": "per-feature tower widths"},
    {"name": "residual", "type": bool, "default": False,
     "help": "residual connections inside towers"},
    {"name": "samples", "type": int, "default": 512,
     "help": "synthetic sample count"},
]

required = {"batch_size", "epochs", "learning_rate", "dense",
            "activation", "loss"}


class BenchmarkUno(Benchmark):
    def set_locals(self):
        self.required = set(required)
        self.additional_definitions = additional_definitions
