"""Elementwise-op exercise (reference: examples/python/keras/unary.py;
tests/multi_gpu_tests.sh): Activation layers + Add/Subtract/Multiply
merges through the Keras frontend.

  python examples/python/keras/unary.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = keras.layers.Input((64,))
    t = keras.layers.Dense(64)(inp)
    t = keras.layers.Activation("relu")(t)
    u = keras.layers.Dense(64)(inp)
    u = keras.layers.Activation("sigmoid")(u)
    s = keras.layers.Add()([t, u])
    d = keras.layers.Subtract()([t, u])
    m = keras.layers.Multiply()([s, d])
    m = keras.layers.Activation("tanh")(m)
    out = keras.layers.Dense(4, activation="softmax")(m)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 64).astype(np.float32)
    y = rng.randint(0, 4, 256).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
