"""Sequential-model CIFAR-10 CNN (reference:
examples/python/keras/seq_cifar10_cnn.py; tests/multi_gpu_tests.sh).

  python examples/python/keras/seq_cifar10_cnn.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    model = keras.Sequential([
        keras.layers.Conv2D(32, (3, 3), padding="same", activation="relu",
                            input_shape=(3, 32, 32)),
        keras.layers.Conv2D(32, (3, 3), padding="same", activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Conv2D(64, (3, 3), padding="same", activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Flatten(),
        keras.layers.Dense(256, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
