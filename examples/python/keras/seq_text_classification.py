"""Keras text classification: Embedding -> GlobalAveragePooling1D ->
Dense — the standard keras text head (no direct reference example; the
reference keras zoo is image-only, SURVEY §2.7). Synthetic separable
token sequences.

  python examples/python/keras/seq_text_classification.py -e 2
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 2
    vocab, seq_len, classes = 200, 16, 4

    model = keras.Sequential([
        keras.layers.Embedding(vocab, 32, input_shape=(seq_len,)),
        keras.layers.GlobalAveragePooling1D(),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(classes, activation="softmax"),
    ])
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randint(0, vocab, (512, seq_len)).astype(np.int32)
    # quantile-binned mean token id: all four classes populated (a
    # plain mean/vocab bucket concentrates near the middle and only
    # fills two), and the signal is exactly what mean pooling preserves
    m = x.mean(axis=1)
    y = np.digitize(m, np.quantile(m, [0.25, 0.5, 0.75])).astype(np.int32)
    hist = model.fit(x, y, batch_size=64, epochs=epochs, verbose=True)
    print(f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
