"""candle_uno on the Keras frontend (reference:
examples/python/keras/candle_uno/ — cancer-drug-response MLP with
multiple feature towers concatenated; examples/cpp/candle_uno).

  python examples/python/keras/candle_uno.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


FEATURE_SHAPES = {"dose": 1, "cell.rnaseq": 64, "drug.descriptors": 48}


def tower(width_list, inp):
    t = inp
    for w in width_list:
        t = keras.layers.Dense(w, activation="relu")(t)
    return t


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inputs, towers = [], []
    for name, dim in FEATURE_SHAPES.items():
        inp = keras.layers.Input((dim,))
        inputs.append(inp)
        towers.append(tower([64, 64], inp) if dim > 1 else inp)
    t = keras.layers.Concatenate(axis=1)(towers)
    for _ in range(3):
        t = keras.layers.Dense(128, activation="relu")(t)
    out = keras.layers.Dense(1)(t)
    model = keras.Model(inputs=inputs, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="mean_squared_error", metrics=["mse"])

    rng = np.random.RandomState(0)
    n = 512
    xs = [rng.randn(n, d).astype(np.float32)
          for d in FEATURE_SHAPES.values()]
    y = rng.rand(n, 1).astype(np.float32)
    hist = model.fit(xs, y, batch_size=64, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
