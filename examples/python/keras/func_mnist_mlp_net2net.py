"""Functional-API Net2Net example (reference:
examples/python/keras/func_mnist_mlp_net2net.py; tests/multi_gpu_tests.sh):
teacher -> widened student with teacher-seeded weights, functional API.

  python examples/python/keras/func_mnist_mlp_net2net.py -e 2
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def make(width):
    inp = keras.layers.Input((784,))
    t = keras.layers.Dense(width, activation="relu")(inp)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    return model


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 2

    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)

    teacher = make(128)
    teacher.fit(x, y, batch_size=64, epochs=epochs)

    student = make(256)
    s_ff = student.build_model(batch_size=64)
    t_ff = teacher.ffmodel
    t_ops = [op.name for op in t_ff.ops if op.op_type == "linear"]
    s_ops = [op.name for op in s_ff.ops if op.op_type == "linear"]
    tw0 = t_ff.get_weights(t_ops[0])
    sw0 = {k: v.copy() for k, v in s_ff.get_weights(s_ops[0]).items()}
    sw0["kernel"][:, :128] = tw0["kernel"]
    sw0["bias"][:128] = tw0["bias"]
    s_ff.set_weights(s_ops[0], sw0)
    tw1 = t_ff.get_weights(t_ops[1])
    sw1 = {k: v.copy() for k, v in s_ff.get_weights(s_ops[1]).items()}
    sw1["kernel"][:128, :] = tw1["kernel"]
    sw1["bias"][:] = tw1["bias"]
    s_ff.set_weights(s_ops[1], sw1)

    hist = student.fit(x, y, batch_size=64, epochs=epochs)
    print(f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
