"""Sequential model nesting a Sequential feature extractor (reference:
examples/python/keras/seq_mnist_cnn_nested.py; tests/multi_gpu_tests.sh).

  python examples/python/keras/seq_mnist_cnn_nested.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    features = keras.Sequential([
        keras.layers.Conv2D(32, (3, 3), activation="relu",
                            input_shape=(1, 28, 28)),
        keras.layers.MaxPooling2D((2, 2)),
    ], name="features")

    inp = keras.layers.Input((1, 28, 28))
    t = features(inp)                  # nested Sequential as a layer
    t = keras.layers.Flatten()(t)
    t = keras.layers.Dense(64, activation="relu")(t)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
