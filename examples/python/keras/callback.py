"""Callback example (reference: examples/python/keras/callback.py;
tests/multi_gpu_tests.sh): EarlyStopping + LearningRateScheduler (the
schedule's lr rides the compiled step as a traced scalar — per-epoch
changes never recompile) + the accuracy-verification callback from
accuracy_tests.sh.

  python examples/python/keras/callback.py -e 10
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 10

    model = keras.Sequential([
        keras.layers.Dense(128, activation="relu", input_shape=(64,)),
        keras.layers.Dense(4, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(512, 64).astype(np.float32)
    w = rng.randn(64, 4).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)

    stop = keras.EarlyStopping(monitor="loss", patience=2, min_delta=1e-4)
    sched = keras.LearningRateScheduler(lambda e: 0.1 * (0.9 ** e))
    hist = model.fit(x, y, batch_size=64, epochs=epochs,
                     callbacks=[stop, sched])
    print(f"trained {len(hist)} epochs (early stop at patience=2, "
          f"final lr {model.ffmodel.get_learning_rate():.4f}); "
          f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
