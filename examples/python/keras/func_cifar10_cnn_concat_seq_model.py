"""Functional CIFAR-10 CNN concatenating a SEQUENTIAL model's output
with a functional branch (reference:
examples/python/keras/func_cifar10_cnn_concat_seq_model.py;
tests/multi_gpu_tests.sh): Sequential-as-layer + Model-as-layer mixed.

  python examples/python/keras/func_cifar10_cnn_concat_seq_model.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    seq_branch = keras.Sequential([
        keras.layers.Conv2D(32, (3, 3), padding="same",
                            activation="relu", input_shape=(3, 32, 32)),
        keras.layers.MaxPooling2D((2, 2)),
    ])

    inp = keras.layers.Input((3, 32, 32))
    a = seq_branch(inp)                     # Sequential used as a layer
    b = keras.layers.Conv2D(32, (1, 1), activation="relu")(inp)
    b = keras.layers.MaxPooling2D((2, 2))(b)
    t = keras.layers.Concatenate(axis=1)([a, b])
    t = keras.layers.Flatten()(t)
    t = keras.layers.Dense(128, activation="relu")(t)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
