"""Sequential-model MNIST MLP (reference:
examples/python/keras/seq_mnist_mlp.py; first entry in
tests/multi_gpu_tests.sh).

  python examples/python/keras/seq_mnist_mlp.py -e 3 --accuracy
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 2

    model = keras.Sequential([
        keras.layers.Dense(512, activation="relu", input_shape=(784,)),
        keras.layers.Dropout(0.2),
        keras.layers.Dense(512, activation="relu"),
        keras.layers.Dropout(0.2),
        keras.layers.Dense(10, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    hist = model.fit(x, y, batch_size=64, epochs=epochs)
    acc = hist[-1]["accuracy"]
    print(f"final accuracy: {acc:.3f}")
    if "--accuracy" in sys.argv:
        assert acc > 0.3, acc


if __name__ == "__main__":
    top_level_task()
