"""Functional-API multi-branch model with Concatenate.

Reference: examples/python/keras/ concatenation examples
(func_cifar10_cnn_concat.py family) — two conv towers over the same
input merged by Concatenate, exercising the Concat op and multi-branch
graph emission.

  python examples/python/keras/multi_branch_concat.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = keras.layers.Input((3, 32, 32))
    a = keras.layers.Conv2D(16, (3, 3), padding="same",
                            activation="relu")(inp)
    b = keras.layers.Conv2D(16, (5, 5), padding="same",
                            activation="relu")(inp)
    t = keras.layers.Concatenate(axis=1)([a, b])
    t = keras.layers.MaxPooling2D((2, 2))(t)
    t = keras.layers.Flatten()(t)
    t = keras.layers.Dense(128, activation="relu")(t)
    out = keras.layers.Dense(10, activation="softmax")(t)

    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.Adam(learning_rate=1e-3),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)
    history = model.fit(x, y, batch_size=32, epochs=epochs)
    print("final:", history[-1])


if __name__ == "__main__":
    top_level_task()
