"""Expected-accuracy floors for the keras example zoo (reference:
examples/python/keras/accuracy.py — the enum the CI accuracy tests
assert against)."""

from enum import Enum


class ModelAccuracy(Enum):
    MNIST_MLP = 90.0
    MNIST_CNN = 98.0
    REUTERS_MLP = 78.0
    CIFAR10_CNN = 78.0
    CIFAR10_ALEXNET = 71.0
