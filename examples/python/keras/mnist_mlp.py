"""Keras-frontend MNIST-style MLP (reference:
examples/python/keras/mnist_mlp.py).  Uses synthetic data shaped like
MNIST; pass --accuracy to assert the model learns (reference -a flag /
accuracy_tests.sh pattern).

  python examples/python/keras/mnist_mlp.py -e 3
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 2

    model = keras.Sequential([
        keras.layers.Dense(512, activation="relu", input_shape=(784,)),
        keras.layers.Dense(512, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    # synthetic, but learnable: labels depend on the inputs
    rng = np.random.RandomState(0)
    x = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)

    history = model.fit(x, y, batch_size=64, epochs=epochs)
    acc = history[-1]["accuracy"]
    print(f"final accuracy: {acc:.3f}")
    if "--accuracy" in sys.argv:
        assert acc > 0.3, f"model failed to learn (accuracy {acc:.3f})"


if __name__ == "__main__":
    top_level_task()
