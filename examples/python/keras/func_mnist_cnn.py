"""Functional-API MNIST CNN (reference:
examples/python/keras/func_mnist_cnn.py; tests/multi_gpu_tests.sh).

  python examples/python/keras/func_mnist_cnn.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    inp = keras.layers.Input((1, 28, 28))
    t = keras.layers.Conv2D(32, (3, 3), activation="relu")(inp)
    t = keras.layers.Conv2D(64, (3, 3), activation="relu")(t)
    t = keras.layers.MaxPooling2D((2, 2))(t)
    t = keras.layers.Flatten()(t)
    t = keras.layers.Dense(128, activation="relu")(t)
    out = keras.layers.Dense(10, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=out)
    model.compile(optimizer=keras.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    rng = np.random.RandomState(0)
    x = rng.randn(256, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)
    hist = model.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
