"""Reuters topic-classification MLP, Sequential-API variant
(reference: examples/python/keras/seq_reuters_mlp.py — the Sequential
twin of reuters_mlp.py's functional build).

  python examples/python/keras/seq_reuters_mlp.py -e 1
"""

import argparse

import numpy as np

from flexflow_tpu.frontends import keras
from flexflow_tpu.frontends.keras.datasets import reuters


def vectorize(seqs, dim):
    out = np.zeros((len(seqs), dim), np.float32)
    for i, s in enumerate(seqs):
        out[i, np.asarray(list(s), np.int64) % dim] = 1.0
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-e", "--epochs", type=int, default=2)
    ap.add_argument("--max-words", type=int, default=1000)
    ap.add_argument("-n", "--samples", type=int, default=2048)
    args, _ = ap.parse_known_args()

    (x_train, y_train), _ = reuters.load_data(num_words=args.max_words)
    x = vectorize(x_train[:args.samples], args.max_words)
    y = np.asarray(y_train[:args.samples], np.int32)
    classes = max(46, int(y.max()) + 1)

    model = keras.Sequential([
        keras.layers.Dense(512, activation="relu",
                           input_shape=(args.max_words,)),
        keras.layers.Dense(classes, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    hist = model.fit(x, y, batch_size=64, epochs=args.epochs)
    print(f"final accuracy: {hist[-1]['accuracy']:.4f}")


if __name__ == "__main__":
    main()
