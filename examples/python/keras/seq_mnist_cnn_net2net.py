"""Sequential Net2Net on a CNN (reference:
examples/python/keras/seq_mnist_cnn_net2net.py; tests/multi_gpu_tests.sh):
widen the conv stack's channel count, seed from the teacher via host
get/set weights (the reference Parameter::get/set role).

  python examples/python/keras/seq_mnist_cnn_net2net.py -e 1
"""

import sys

import numpy as np

from flexflow_tpu.frontends import keras


def make(channels):
    model = keras.Sequential([
        keras.layers.Conv2D(channels, (3, 3), activation="relu",
                            input_shape=(1, 28, 28)),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Flatten(),
        keras.layers.Dense(10, activation="softmax"),
    ])
    model.compile(optimizer=keras.SGD(learning_rate=0.02),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    return model


def top_level_task():
    epochs = int(sys.argv[sys.argv.index("-e") + 1]) \
        if "-e" in sys.argv else 1

    rng = np.random.RandomState(0)
    x = rng.randn(256, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int32)

    teacher = make(16)
    teacher.fit(x, y, batch_size=32, epochs=epochs)

    student = make(32)
    s_ff = student.build_model(batch_size=32)
    t_ff = teacher.ffmodel
    t_conv = next(op.name for op in t_ff.ops if op.op_type == "conv2d")
    s_conv = next(op.name for op in s_ff.ops if op.op_type == "conv2d")
    tw = t_ff.get_weights(t_conv)
    sw = {k: v.copy() for k, v in s_ff.get_weights(s_conv).items()}
    sw["kernel"][:16] = tw["kernel"]  # OIHW: copy the teacher's filters
    sw["bias"][:16] = tw["bias"]
    s_ff.set_weights(s_conv, sw)

    hist = student.fit(x, y, batch_size=32, epochs=epochs)
    print(f"final accuracy: {hist[-1]['accuracy']:.3f}")


if __name__ == "__main__":
    top_level_task()
