"""CIFAR-10 CNN with concatenated conv branches on the native builder
API (reference: examples/python/native/cifar10_cnn_concat.py; run by
tests/multi_gpu_tests.sh).

  python -m flexflow_tpu examples/python/native/cifar10_cnn_concat.py -b 16 -e 1
"""

import sys

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 3, 32, 32), name="input")
    a = ff.conv2d(x, 32, 3, 3, 1, 1, 1, 1, activation="relu", name="br_a")
    b = ff.conv2d(x, 32, 5, 5, 1, 1, 2, 2, activation="relu", name="br_b")
    t = ff.concat([a, b], axis=1)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 256, activation="relu")
    t = ff.dense(t, 10)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    n = 256
    if "--samples" in sys.argv:
        n = int(sys.argv[sys.argv.index("--samples") + 1])
    xs, ys = synthetic_dataset(ff, n, num_classes=10, seed=cfg.seed)
    hist = ff.fit(xs, ys, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
