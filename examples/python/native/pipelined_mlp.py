"""Graph pipelining: train a plain-layer MLP split into pipeline
stages by whole-op device pins (the executable form of the reference's
per-op device placement, mapper.cc:346-440 — here stages stream
microbatches over a mesh `pipe` axis, core/staged.py).

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -m flexflow_tpu examples/python/native/pipelined_mlp.py \
      -b 64 -e 2 --pipeline-schedule 1f1b
"""

import sys

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh
from flexflow_tpu.parallel.pconfig import DEVICE_KEY, OpStrategy, Strategy


def top_level_task():
    cfg = FFConfig.from_args()
    import jax
    n = len(jax.devices())
    if n < 2:
        print("needs >= 2 devices (set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return
    mesh = make_mesh((n // 2, 2), ("data", "pipe"))

    # stage 0 = the wide trunk, stage 1 = the head (pins; unpinned ops
    # inherit their producers' stage)
    strat = Strategy(default=OpStrategy({}))
    strat.set("fc1", OpStrategy({DEVICE_KEY: (0,)}))
    strat.set("fc3", OpStrategy({DEVICE_KEY: (1,)}))

    ff = FFModel(cfg, mesh=mesh, strategy=strat)
    x = ff.create_tensor((cfg.batch_size, 784), name="input")
    t = ff.dense(x, 512, activation="relu", name="fc1")
    t = ff.dense(t, 512, activation="relu", name="fc2")
    t = ff.dense(t, 10, name="fc3")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], mesh=mesh, strategy=strat)

    from flexflow_tpu.core.staged import StagedExecutor
    assert isinstance(ff.executor, StagedExecutor), (
        "pins did not lower to pipeline stages")
    print(f"stages: {[[o.name for o in s] for s in ff.executor.plan.stages]}"
          f"  schedule: {ff.executor.schedule}")

    rng = np.random.RandomState(cfg.seed)
    xs = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1).astype(np.int32)
    hist = ff.fit({"input": xs}, ys, epochs=cfg.epochs)
    acc = hist[-1]["accuracy"]
    print(f"final accuracy: {acc:.3f}")
    if "--accuracy" in sys.argv:
        assert acc > 0.3, f"model failed to learn ({acc:.3f})"


if __name__ == "__main__":
    top_level_task()
