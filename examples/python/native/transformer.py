"""Transformer encoder training (reference: examples/cpp/Transformer —
512 hidden / 8 heads encoder blocks over synthetic data,
transformer.cc:28-56).

  python examples/python/native/transformer.py -b 32 -e 1
  python examples/python/native/transformer.py --search-budget 1000 \
      --enable-parameter-parallel      # strategy search before training
"""

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.models import build_transformer

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = build_transformer(cfg, seq_len=64, hidden=512, num_heads=8,
                           num_layers=2, ff_dim=2048, num_classes=10)
    ff.compile(optimizer=SGDOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_dataset(ff, 4 * cfg.batch_size, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
