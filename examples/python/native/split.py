"""Split/concat round-trip example (reference:
examples/python/native/split.py; run by tests/multi_gpu_tests.sh).

  python -m flexflow_tpu examples/python/native/split.py -b 32 -e 1
"""

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 64), name="input")
    a, b = ff.split(x, 2, axis=1)       # two (bs, 32) halves
    a = ff.dense(a, 32, activation="relu")
    b = ff.dense(b, 32, activation="tanh")
    t = ff.concat([a, b], axis=1)
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    xs, ys = synthetic_dataset(ff, 256, num_classes=10, seed=cfg.seed)
    hist = ff.fit(xs, ys, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
