"""Stacked-LSTM NMT-style language model (reference: nmt/ — rebuilt as an
ordinary model of the main framework per SURVEY.md section 7 step 8, not
as a separate RNN framework).

  python examples/python/native/nmt_lstm.py -b 32 -e 1
"""

from flexflow_tpu import AdamOptimizer, FFConfig
from flexflow_tpu.models import build_nmt_lstm

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    vocab = 2000
    ff = build_nmt_lstm(cfg, seq_len=20, vocab_size=vocab, embed_dim=128,
                        hidden=128, num_layers=2)
    ff.compile(optimizer=AdamOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_dataset(ff, 4 * cfg.batch_size, num_classes=vocab,
                             int_high=vocab, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
