"""Expected-accuracy floors for the native example zoo (reference:
examples/python/native/accuracy.py — the enum the CI accuracy tests
assert against; see tests/test_examples.py for the asserting suite)."""

from enum import Enum


class ModelAccuracy(Enum):
    MNIST_MLP = 90.0
    MNIST_CNN = 98.0
    CIFAR10_CNN = 78.0
    CIFAR10_ALEXNET = 71.0
