"""Inception-v3 training (reference: examples/cpp/InceptionV3).

  python examples/python/native/inception_v3.py -b 8 -e 1
"""

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.models import build_inception_v3

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = build_inception_v3(cfg, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_dataset(ff, 2 * cfg.batch_size, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
