"""Long-context training: sequence parallelism over a `seq` mesh axis.

The reference has no sequence-parallel axis at all (SURVEY.md 2.4);
this framework ships two TPU-native lowerings and picks per shape:

  * ring attention  — K/V shards rotate over ICI (`lax.ppermute`),
    scores never materialize: arbitrary sequence lengths.
  * all-to-all      — heads scatter while the sequence gathers
    (DeepSpeed-Ulysses pattern): full-sequence MXU blocks + the flash
    kernel, when heads divide the axis and scores fit.

Run (8 virtual CPU devices stand in for a TPU slice):

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -m flexflow_tpu examples/python/native/long_context_attention.py \
      -b 8 -e 2 --sp-attention auto
"""

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, make_mesh
from flexflow_tpu.parallel.pconfig import sequence_parallel_strategy

SEQ = 512
HIDDEN = 64
CLASSES = 4


def top_level_task():
    cfg = FFConfig.from_args()
    import jax
    n = len(jax.devices())
    if n < 2:
        print("needs >= 2 devices (set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return
    # batch over `data`, sequence over `seq`: tokens of one example
    # live across devices, attention runs sequence-parallel
    mesh = make_mesh((max(1, n // 4), min(4, n)), ("data", "seq"))
    cfg.enable_sequence_parallel = True

    ff = FFModel(cfg, mesh=mesh, strategy=sequence_parallel_strategy())
    x = ff.create_tensor((cfg.batch_size, SEQ, HIDDEN), name="input")
    t = ff.multihead_attention(x, x, x, HIDDEN, 8, causal=True,
                               name="attn0")
    t = ff.dense(t, HIDDEN, activation="relu", name="ffn0")
    t = ff.multihead_attention(t, t, t, HIDDEN, 8, causal=True,
                               name="attn1")
    # mean-pool the sequence, classify
    t = ff.reduce_mean(t, axis=1, name="pool")
    ff.softmax(ff.dense(t, CLASSES, name="head"))
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"], mesh=mesh)

    rng = np.random.RandomState(0)
    x_np = rng.randn(cfg.batch_size * 4, SEQ, HIDDEN).astype(np.float32)
    y_np = rng.randint(0, CLASSES, cfg.batch_size * 4).astype(np.int32)
    ff.fit({"input": x_np}, y_np, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
