"""Host tensor attach/get/set roundtrip (reference:
examples/python/native/tensor_attach.py — numpy attach_raw_ptr +
inline map; here the host get/set_weights path plus a dataloader
built straight over attached numpy arrays).

  python -m flexflow_tpu examples/python/native/tensor_attach.py -b 32 -e 1
"""

import numpy as np

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    ff = FFModel(cfg)
    x = ff.create_tensor((bs, 16), name="input")
    t = ff.dense(x, 32, activation="relu", name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    # "attach" pretrained host weights (Parameter::set_weights role)
    rng = np.random.RandomState(cfg.seed)
    w = {"kernel": (rng.randn(16, 32) * 0.1).astype(np.float32),
         "bias": np.zeros(32, np.float32)}
    ff.set_weights("fc1", w)
    back = ff.get_weights("fc1")
    np.testing.assert_allclose(back["kernel"], w["kernel"], rtol=1e-6)
    print("attach roundtrip OK")

    # dataloaders over attached numpy buffers (SingleDataLoader role)
    xs = rng.randn(8 * bs, 16).astype(np.float32)
    ys = rng.randint(0, 4, 8 * bs).astype(np.int32)
    loader_x = ff.create_data_loader("input", xs)
    loader_y = ff.create_data_loader("label", ys)
    m = None
    for _ in range(len(ys) // bs):
        batch = {"input": loader_x.next_batch(),
                 "label": loader_y.next_batch()}
        m = ff.train_batch(batch)
    print(f"final loss: {float(m['loss']):.4f}")


if __name__ == "__main__":
    top_level_task()
