"""SingleDataLoader example (reference:
examples/python/native/mnist_mlp_attach.py — attach full numpy datasets
to per-tensor loaders and drive training with next_batch, the
flexflow_dataloader.cc:649-740 pattern).

  python -m flexflow_tpu examples/python/native/mnist_mlp_attach.py -e 2
"""

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    ff = FFModel(cfg)
    x = ff.create_tensor((bs, 784), name="input")
    t = ff.dense(x, 256, activation="relu")
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(cfg.seed)
    xs = rng.randn(512, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1).astype(np.int32)

    # explicit per-tensor loaders + next_batch loop (reference
    # SingleDataLoader drive, alexnet.cc:97-113)
    x_loader = ff.create_data_loader("input", xs)
    y_loader = ff.create_data_loader("label", ys)
    steps = len(ys) // bs
    for epoch in range(cfg.epochs):
        x_loader.reset()
        y_loader.reset()
        last = None
        for _ in range(steps):
            batch = {"input": x_loader.next_batch(),
                     "label": y_loader.next_batch()}
            last = ff.train_batch(batch)
        print(f"epoch {epoch}: loss={float(last['loss']):.4f}")


if __name__ == "__main__":
    top_level_task()
