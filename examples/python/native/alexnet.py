"""AlexNet training (reference: examples/cpp/AlexNet/alexnet.cc:34-137,
bootcamp_demo/ff_alexnet_cifar10.py).

  python -m flexflow_tpu examples/python/native/alexnet.py -b 64 -e 2
  python examples/python/native/alexnet.py --samples 512   # synthetic
"""

import sys

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.models import build_alexnet

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    n_samples = 256
    if "--samples" in sys.argv:
        n_samples = int(sys.argv[sys.argv.index("--samples") + 1])

    ff = build_alexnet(cfg, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    print(ff.summary())

    x, y = synthetic_dataset(ff, n_samples, num_classes=10, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
