"""CANDLE-Uno drug-response regression (reference: examples/cpp/candle_uno
— per-feature dense towers concatenated into a final MLP).

  python examples/python/native/candle_uno.py -b 32 -e 1
"""

from flexflow_tpu import AdamOptimizer, FFConfig
from flexflow_tpu.models import build_candle_uno

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = build_candle_uno(cfg)
    ff.compile(optimizer=AdamOptimizer(lr=cfg.learning_rate),
               loss_type="mean_squared_error", metrics=[])
    x, y = synthetic_dataset(ff, 4 * cfg.batch_size, regression=True,
                             seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
