"""Reshape/transpose exercise (reference:
examples/python/native/reshape.py; tests/multi_gpu_tests.sh).

  python -m flexflow_tpu examples/python/native/reshape.py -e 1
"""

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    ff = FFModel(cfg)
    x = ff.create_tensor((bs, 8, 8), name="input")
    t = ff.reshape(x, (bs, 64))
    t = ff.dense(t, 64, activation="relu")
    t = ff.reshape(t, (bs, 8, 8))
    t = ff.transpose(t, [0, 2, 1])
    t = ff.reshape(t, (bs, 64))
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    xs, ys = synthetic_dataset(ff, 256, num_classes=10, seed=cfg.seed)
    hist = ff.fit(xs, ys, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
