"""Shared example plumbing: synthetic datasets shaped to a model's
declared inputs (reference: syntheticInput when no --dataset is given,
examples/cpp/AlexNet/alexnet.cc:100-104)."""

import numpy as np
import jax.numpy as jnp


def synthetic_dataset(ff, n_samples: int, num_classes: int = 10,
                      seed: int = 0, regression: bool = False,
                      int_high: int = 10):
    """(x dict, y) with n_samples rows matching ff's input tensors."""
    rng = np.random.RandomState(seed)
    x = {}
    for t in ff.input_tensors:
        shape = (n_samples,) + tuple(t.shape[1:])
        if jnp.issubdtype(t.dtype, jnp.integer):
            x[t.name] = rng.randint(0, int_high, shape).astype(np.int32)
        else:
            x[t.name] = rng.randn(*shape).astype(np.float32)
    if regression:
        y = rng.randn(n_samples, 1).astype(np.float32)
    else:
        y = rng.randint(0, num_classes, n_samples).astype(np.int32)
    return x, y
