"""Shared example plumbing: synthetic datasets shaped to a model's
declared inputs (reference: syntheticInput when no --dataset is given,
examples/cpp/AlexNet/alexnet.cc:100-104)."""

import numpy as np

from flexflow_tpu.core.dataloader import synthetic_inputs


def synthetic_dataset(ff, n_samples: int, num_classes: int = 10,
                      seed: int = 0, regression: bool = False,
                      int_high: int = 10):
    """(x dict, y) with n_samples rows matching ff's input tensors."""
    x = synthetic_inputs(ff, n_samples, seed=seed, int_high=int_high)
    rng = np.random.RandomState(seed + 1)
    if regression:
        y = rng.randn(n_samples, 1).astype(np.float32)
    else:
        y = rng.randint(0, num_classes, n_samples).astype(np.int32)
    return x, y
