"""Standalone MultiHeadAttention training example (reference:
examples/python/native/multi_head_attention.py — the op that maps to
cuDNN fused MHA, attention.cu:245; here the Pallas flash / XLA path).

  python -m flexflow_tpu examples/python/native/multi_head_attention.py -b 16 -e 1
"""

import numpy as np

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel


def top_level_task():
    cfg = FFConfig.from_args()
    bs, seq, hidden = cfg.batch_size, 32, 64
    ff = FFModel(cfg)
    q = ff.create_tensor((bs, seq, hidden), name="input")
    t = ff.multihead_attention(q, q, q, embed_dim=hidden, num_heads=4,
                               name="mha")
    t = ff.reshape(t, (bs, seq * hidden))
    t = ff.dense(t, 10)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(cfg.seed)
    x = rng.randn(8 * bs, seq, hidden).astype(np.float32)
    y = rng.randint(0, 10, 8 * bs).astype(np.int32)
    hist = ff.fit({"input": x}, y, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
