"""MNIST-style MLP on the native builder API (reference:
examples/python/native/mnist_mlp.py; run by tests/multi_gpu_tests.sh).

  python -m flexflow_tpu examples/python/native/mnist_mlp.py -b 64 -e 3
"""

import sys

import numpy as np

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel


def top_level_task():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 784), name="input")
    t = ff.dense(x, 512, activation="relu")
    t = ff.dense(t, 512, activation="relu")
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    # synthetic but learnable: labels depend linearly on the inputs
    rng = np.random.RandomState(cfg.seed)
    xs = rng.randn(1024, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1).astype(np.int32)
    hist = ff.fit({"input": xs}, ys, epochs=cfg.epochs)
    acc = hist[-1]["accuracy"]
    print(f"final accuracy: {acc:.3f}")
    if "--accuracy" in sys.argv:
        assert acc > 0.3, f"model failed to learn ({acc:.3f})"


if __name__ == "__main__":
    top_level_task()
