"""CIFAR-10 CNN driven by attached per-tensor data loaders (reference:
examples/python/native/cifar10_cnn_attach.py — the SingleDataLoader
attach variant of cifar10_cnn.py; see mnist_mlp_attach.py for the MLP
twin).

  python -m flexflow_tpu examples/python/native/cifar10_cnn_attach.py -e 1
"""

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    ff = FFModel(cfg)
    x = ff.create_tensor((bs, 3, 32, 32), name="input")
    t = ff.conv2d(x, 32, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 512, activation="relu")
    t = ff.dense(t, 10)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    import sys
    n = 64
    if "--samples" in sys.argv:
        n = int(sys.argv[sys.argv.index("--samples") + 1])
    rng = np.random.RandomState(cfg.seed)
    xs = rng.randn(n, 3, 32, 32).astype(np.float32)
    ys = rng.randint(0, 10, (n,)).astype(np.int32)

    x_loader = ff.create_data_loader("input", xs)
    y_loader = ff.create_data_loader("label", ys)
    steps = n // bs
    for epoch in range(cfg.epochs):
        x_loader.reset()
        y_loader.reset()
        last = None
        for _ in range(steps):
            batch = {"input": x_loader.next_batch(),
                     "label": y_loader.next_batch()}
            last = ff.train_batch(batch)
        print(f"epoch {epoch}: loss={float(last['loss']):.4f}")


if __name__ == "__main__":
    top_level_task()
