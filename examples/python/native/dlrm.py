"""DLRM CTR training (reference: examples/cpp/DLRM/dlrm.cc:26-124 —
bottom MLP, per-feature embedding bags, pairwise interaction, top MLP).
The reference's per-GPU embedding placement (strategies/dlrm_strategy.cc)
maps to sharding each table's vocab over the mesh `model` axis.

  python examples/python/native/dlrm.py -b 64 -e 1
"""

from flexflow_tpu import AdamOptimizer, FFConfig
from flexflow_tpu.models import build_dlrm

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = build_dlrm(cfg, embedding_vocab_sizes=(1000,) * 8,
                    embedding_dim=64)
    ff.compile(optimizer=AdamOptimizer(lr=cfg.learning_rate),
               loss_type="mean_squared_error", metrics=[])
    x, y = synthetic_dataset(ff, 4 * cfg.batch_size, regression=True,
                             int_high=1000, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
