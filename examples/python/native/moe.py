"""Mixture-of-experts training (reference: examples/cpp/mixture_of_experts/
moe.cc — gating softmax + top-k + group_by + experts + aggregate).

Two variants: --reference uses the explicit group_by/aggregate pipeline
(op-parity with the reference); the default uses the fused MoE FFN op
(TPU-first: capacity-bucketed einsum dispatch, EP over the mesh).

  python examples/python/native/moe.py -b 64 -e 1
  python examples/python/native/moe.py --reference
"""

import sys

from flexflow_tpu import AdamOptimizer, FFConfig
from flexflow_tpu.models import build_moe_fused, build_moe_reference

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    build = build_moe_reference if "--reference" in sys.argv \
        else build_moe_fused
    ff = build(cfg, input_dim=64, num_experts=4, k=2)
    ff.compile(optimizer=AdamOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_dataset(ff, 4 * cfg.batch_size, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
