"""ResNet training (reference: examples/cpp/ResNet).

  python examples/python/native/resnet.py -b 32 -e 1 --depth 18
"""

import sys

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.models import build_resnet

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    depth = int(sys.argv[sys.argv.index("--depth") + 1]) \
        if "--depth" in sys.argv else 18

    ff = build_resnet(cfg, depth=depth, image_size=32)
    ff.compile(optimizer=SGDOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    x, y = synthetic_dataset(ff, 4 * cfg.batch_size, seed=cfg.seed)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
