"""Inline-map demo: read and mutate attached tensor data on the host
(reference: examples/python/native/print_input.py —
inline_map/get_array over input tensors; here the analog is the
data-loader attach + host-side numpy views, since JAX arrays are
host-visible by construction).

  python -m flexflow_tpu examples/python/native/print_input.py
"""

import numpy as np

from flexflow_tpu import FFConfig, FFModel


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    ff = FFModel(cfg)
    ff.create_tensor((bs, 3, 8, 8), name="input1")
    ff.create_tensor((bs, 256), name="input2")

    rng = np.random.RandomState(cfg.seed)
    x1 = rng.randn(bs * 2, 3, 8, 8).astype(np.float32)
    x2 = np.zeros((bs * 2, 256), np.float32) + 2.2

    loader1 = ff.create_data_loader("input1", x1)
    loader2 = ff.create_data_loader("input2", x2)
    loader1.reset()
    loader2.reset()
    b1 = np.asarray(loader1.next_batch())
    b2 = np.asarray(loader2.next_batch())
    print(b1.shape)
    print(b1)
    print(b2.shape)
    print(b2)
    assert b1.shape == (bs, 3, 8, 8)
    assert float(b2[0, 0]) == np.float32(2.2)
    print("print_input OK")


if __name__ == "__main__":
    top_level_task()
