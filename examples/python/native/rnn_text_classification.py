"""RNN text classification (RNNTC) — one of the MLSys'19 paper's
benchmark workloads (BASELINE.md speedup table: "RNNTC, RNNLM, NMT")
that has no reference example script. Embedding -> stacked LSTM (last
hidden state) -> dense classifier, on synthetic token sequences.

  python examples/python/native/rnn_text_classification.py -b 32 -e 1
"""

import numpy as np

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel


def top_level_task():
    cfg = FFConfig.from_args()
    vocab, seq_len, classes = 2000, 32, 4
    bs = cfg.batch_size

    ff = FFModel(cfg)
    tokens = ff.create_tensor((bs, seq_len), dtype=np.int32, name="input")
    t = ff.embedding(tokens, vocab, 128, aggr="none", name="embed")
    t = ff.lstm(t, 128, return_sequences=True, name="lstm_0")
    t = ff.lstm(t, 128, return_sequences=False, name="lstm_1")
    t = ff.dense(t, 64, activation="relu", name="fc")
    logits = ff.dense(t, classes, name="classifier")
    ff.softmax(logits)
    ff.compile(optimizer=AdamOptimizer(lr=cfg.learning_rate),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(cfg.seed)
    n = 4 * bs
    x = rng.randint(0, vocab, (n, seq_len)).astype(np.int32)
    # separable synthetic labels: class = leading token bucket
    y = (x[:, 0] * classes // vocab).astype(np.int32)
    ff.fit({"input": x}, y, epochs=cfg.epochs)


if __name__ == "__main__":
    top_level_task()
