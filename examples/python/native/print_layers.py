"""Layer introspection example (reference:
examples/python/native/print_layers.py; run by tests/multi_gpu_tests.sh):
builds a small net, prints the per-op summary, then trains one epoch.

  python -m flexflow_tpu examples/python/native/print_layers.py -e 1
"""

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel

from common import synthetic_dataset


def top_level_task():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 784), name="input")
    t = ff.dense(x, 128, activation="relu", name="fc1")
    t = ff.dropout(t, 0.2, name="drop")
    t = ff.dense(t, 10, name="fc2")
    t = ff.softmax(t, name="probs")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    print(ff.summary())
    for op in ff.ops:
        ws = {n: s.shape for n, s in op.weight_specs().items()}
        print(f"  {op.name:12s} {op.op_type:16s} "
              f"out={op.outputs[0].shape} weights={ws}")

    xs, ys = synthetic_dataset(ff, 128, num_classes=10, seed=cfg.seed)
    hist = ff.fit(xs, ys, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
