"""BERT-proxy: a stack of transformer encoder blocks on the native
builder API (reference: examples/python/native/bert_proxy_native.py —
BERT-Large-shaped MHA+FFN blocks on synthetic data).

Sized down by default so it runs anywhere; pass --hidden/--layers to
scale up toward the reference's 1024/24.

  python -m flexflow_tpu examples/python/native/bert_proxy_native.py -b 8 -e 1
"""

import sys

import numpy as np

from flexflow_tpu import FFConfig, SGDOptimizer, FFModel


def arg(flag, default, typ=int):
    return typ(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    seq = arg("--seq-length", 64)
    hidden = arg("--hidden", 128)
    heads = arg("--heads", 8)
    layers = arg("--layers", 2)

    ff = FFModel(cfg)
    t = ff.create_tensor((bs, seq, hidden), name="input")
    for i in range(layers):
        # self-attention + residual
        a = ff.multihead_attention(t, t, t, embed_dim=hidden,
                                   num_heads=heads, name=f"mha_{i}")
        t = ff.add(t, a, name=f"res_a_{i}")
        # FFN (4x) + residual, GELU like BERT
        f = ff.dense(t, 4 * hidden, activation="gelu", name=f"ffn_up_{i}")
        f = ff.dense(f, hidden, name=f"ffn_down_{i}")
        t = ff.add(t, f, name=f"res_f_{i}")
    t = ff.reshape(t, (bs, seq * hidden))
    t = ff.dense(t, 2)  # NSP-style head
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    rng = np.random.RandomState(cfg.seed)
    x = rng.randn(4 * bs, seq, hidden).astype(np.float32)
    y = rng.randint(0, 2, 4 * bs).astype(np.int32)
    hist = ff.fit({"input": x}, y, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    top_level_task()
