"""Encoder-decoder NMT with attention, teacher-forced (reference: the
standalone nmt/ framework — encoder/decoder LSTM stacks, nmt/rnn.h:91-160,
per-timestep data-parallel softmax softmax_data_parallel.cu — built here
as an ordinary model of the main framework).

  python -m flexflow_tpu examples/python/native/nmt_seq2seq.py -b 16 -e 2
"""

import numpy as np

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.models import build_nmt_seq2seq


def top_level_task():
    cfg = FFConfig.from_args()
    bs = cfg.batch_size
    src_len, tgt_len, vocab = 12, 10, 200

    ff = build_nmt_seq2seq(cfg, batch_size=bs, src_len=src_len,
                           tgt_len=tgt_len, vocab_size=vocab,
                           embed_dim=64, hidden=64)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type="sparse_categorical_crossentropy",
               metrics=["accuracy"])

    # synthetic copy task: target = first tgt_len source tokens
    rng = np.random.RandomState(cfg.seed)
    n = 16 * bs
    src = rng.randint(0, vocab, (n, src_len)).astype(np.int32)
    label = src[:, :tgt_len].astype(np.int32)
    tgt = np.concatenate(  # teacher forcing: <bos>=0 + shifted labels
        [np.zeros((n, 1), np.int32), label[:, :-1]], axis=1)
    hist = ff.fit({"src": src, "tgt": tgt}, label, epochs=cfg.epochs)
    print(f"final loss: {hist[-1]['loss']:.4f} "
          f"accuracy: {hist[-1].get('accuracy', 0):.3f}")


if __name__ == "__main__":
    top_level_task()
